"""Parameter tuning: candidate grids from dataset histograms + vectorized
utility-analysis sweep + RMSE argmin.

Parity: analysis/parameter_tuning.py (TuneOptions :57, TuneResult :97,
candidate generation :120-312, tune :315-440). Candidates are generated
from the dataset-histogram quantile structure; the whole candidate grid is
then evaluated in one vectorized utility-analysis pass (the reference runs
one combiner set per candidate per row).
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from pipelinedp_tpu import input_validators
from pipelinedp_tpu.aggregate_params import (AggregateParams, Metric,
                                             Metrics, NoiseKind)
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu.data_extractors import (DataExtractors,
                                            PreAggregateExtractors)
from pipelinedp_tpu.dataset_histograms import histograms as hist_lib
from pipelinedp_tpu.analysis import data_structures
from pipelinedp_tpu.analysis import dp_strategy_selector as selector_lib
from pipelinedp_tpu.analysis import metrics as metrics_lib
from pipelinedp_tpu.analysis import utility_analysis


class MinimizingFunction(enum.Enum):
    ABSOLUTE_ERROR = "absolute_error"
    RELATIVE_ERROR = "relative_error"


@dataclasses.dataclass
class ParametersToTune:
    """Which AggregateParams attributes the tuner may vary."""
    max_partitions_contributed: bool = False
    max_contributions_per_partition: bool = False
    min_sum_per_partition: bool = False
    max_sum_per_partition: bool = False
    noise_kind: bool = True

    def __post_init__(self):
        if not any(dataclasses.asdict(self).values()):
            raise ValueError("ParametersToTune needs at least one parameter.")


@dataclasses.dataclass
class TuneOptions:
    epsilon: float
    delta: float
    aggregate_params: AggregateParams
    function_to_minimize: Union[MinimizingFunction, Callable]
    parameters_to_tune: ParametersToTune
    partitions_sampling_prob: float = 1
    pre_aggregated_data: bool = False
    number_of_parameter_candidates: int = 100
    # None auto-selects the device sweep (see UtilityAnalysisOptions).
    use_device_sweep: Optional[bool] = None

    def __post_init__(self):
        input_validators.validate_epsilon_delta(self.epsilon, self.delta,
                                                "TuneOptions")


@dataclasses.dataclass
class TuneResult:
    options: TuneOptions
    contribution_histograms: hist_lib.DatasetHistograms
    utility_analysis_parameters: data_structures.MultiParameterConfiguration
    index_best: int
    utility_reports: List[metrics_lib.UtilityReport]


def candidates_constant_relative_step(histogram: hist_lib.Histogram,
                                      max_candidates: int) -> List[int]:
    """Integer candidates 1..max with a constant relative step: the i-th
    candidate is ~max^(i/(k-1)), deduplicated upward."""
    max_value = histogram.max_value()
    if max_value < 1:
        raise ValueError("histogram max_value must be >= 1")
    max_candidates = min(max_candidates, max_value)
    if max_candidates <= 1:
        return [1]
    step = max_value**(1.0 / (max_candidates - 1))
    out = [1]
    acc = 1.0
    for _ in range(1, max_candidates):
        if out[-1] >= max_value:
            break
        acc *= step
        out.append(max(out[-1] + 1, math.ceil(acc)))
    out[-1] = max_value
    return out


def candidates_bin_maximums(histogram: hist_lib.Histogram,
                            max_candidates: int) -> List[float]:
    """Evenly subsampled bin maximums (for sum bounds)."""
    n_bins = len(histogram.bins)
    max_candidates = min(max_candidates, n_bins)
    ids = np.round(np.linspace(0, n_bins - 1, num=max_candidates)).astype(int)
    return [histogram.bins[i].max for i in ids]


def candidates_2d_grid(hist1: hist_lib.Histogram, hist2: hist_lib.Histogram,
                       fn1: Callable, fn2: Callable,
                       max_candidates: int) -> Tuple[List, List]:
    """Cross product of per-parameter candidate lists, rebalanced so a
    parameter with few distinct values frees budget for the other."""
    per_param = int(math.sqrt(max_candidates))
    c1 = fn1(hist1, per_param)
    c2 = fn2(hist2, per_param)
    if len(c2) < per_param and len(c1) == per_param:
        c1 = fn1(hist1, max_candidates // len(c2))
    elif len(c1) < per_param and len(c2) == per_param:
        c2 = fn2(hist2, max_candidates // len(c1))
    grid1, grid2 = [], []
    for a in c1:
        for b in c2:
            grid1.append(a)
            grid2.append(b)
    return grid1, grid2


def find_candidate_parameters(
        hist: hist_lib.DatasetHistograms,
        parameters_to_tune: ParametersToTune,
        metric: Optional[Metric],
        max_candidates: int) -> data_structures.MultiParameterConfiguration:
    """Candidate (l0, linf | max_sum) grid from the dataset histograms."""
    tune_l0 = parameters_to_tune.max_partitions_contributed
    tune_linf = (parameters_to_tune.max_contributions_per_partition and
                 metric == Metrics.COUNT)
    tune_max_sum = (parameters_to_tune.max_sum_per_partition and
                    metric == Metrics.SUM)
    l0 = linf = max_sum = min_sum = None
    if tune_l0 and tune_linf:
        l0, linf = candidates_2d_grid(hist.l0_contributions_histogram,
                                      hist.linf_contributions_histogram,
                                      candidates_constant_relative_step,
                                      candidates_constant_relative_step,
                                      max_candidates)
    elif tune_l0 and tune_max_sum:
        l0, max_sum = candidates_2d_grid(hist.l0_contributions_histogram,
                                         hist.linf_sum_contributions_histogram,
                                         candidates_constant_relative_step,
                                         candidates_bin_maximums,
                                         max_candidates)
        min_sum = [0.0] * len(max_sum)
    elif tune_l0:
        l0 = candidates_constant_relative_step(
            hist.l0_contributions_histogram, max_candidates)
    elif tune_linf:
        linf = candidates_constant_relative_step(
            hist.linf_contributions_histogram, max_candidates)
    elif tune_max_sum:
        max_sum = candidates_bin_maximums(
            hist.linf_sum_contributions_histogram, max_candidates)
        min_sum = [0.0] * len(max_sum)
    else:
        raise ValueError("Nothing to tune.")
    return data_structures.MultiParameterConfiguration(
        max_partitions_contributed=l0,
        max_contributions_per_partition=linf,
        min_sum_per_partition=min_sum,
        max_sum_per_partition=max_sum)


def _attach_dp_strategies(
        config: data_structures.MultiParameterConfiguration,
        blueprint: AggregateParams, fixed_noise_kind: Optional[NoiseKind],
        selector: selector_lib.DPStrategySelector) -> None:
    """Fills per-candidate noise kind / selection strategy in place."""
    # Materialize the candidate params before mutating the swept fields —
    # get_aggregate_params reads them.
    all_params = [
        config.get_aggregate_params(blueprint, i) for i in range(config.size)
    ]
    config.noise_kind = []
    if not selector.is_public_partitions:
        config.partition_selection_strategy = []
        config.post_aggregation_thresholding = []
    for params in all_params:
        if selector.metric is None:
            sensitivities = dp_computations.Sensitivities(
                l0=params.max_partitions_contributed, linf=1)
        else:
            sensitivities = dp_computations.compute_sensitivities(
                selector.metric, params)
        strategy = selector.get_dp_strategy(sensitivities)
        config.noise_kind.append(fixed_noise_kind or strategy.noise_kind)
        if not selector.is_public_partitions:
            config.partition_selection_strategy.append(
                strategy.partition_selection_strategy)
            # Honor the selector's full recommendation: when it chooses
            # post-aggregation thresholding (PRIVACY_ID_COUNT), the swept
            # config analyzes that exact strategy instead of silently
            # falling back to separate-budget selection.
            config.post_aggregation_thresholding.append(
                strategy.post_aggregation_thresholding)


def tune(col,
         backend=None,
         contribution_histograms: hist_lib.DatasetHistograms = None,
         options: TuneOptions = None,
         data_extractors: Union[DataExtractors,
                                PreAggregateExtractors] = None,
         public_partitions=None,
         strategy_selector_factory: Optional[
             selector_lib.DPStrategySelectorFactory] = None
         ) -> Tuple[TuneResult, List]:
    """Finds the best contribution-bounding parameters.

    1. Candidate grid from the dataset histograms.
    2. One vectorized utility-analysis sweep over all candidates.
    3. argmin RMSE of the analyzed metric.

    Returns (TuneResult, per-partition utility analysis results).
    ``backend`` is accepted for signature parity and ignored.
    """
    _check_tune_args(options, public_partitions is not None)
    if strategy_selector_factory is None:
        strategy_selector_factory = selector_lib.DPStrategySelectorFactory()
    metric = (options.aggregate_params.metrics[0]
              if options.aggregate_params.metrics else None)
    candidates = find_candidate_parameters(
        contribution_histograms, options.parameters_to_tune, metric,
        options.number_of_parameter_candidates)
    fixed_noise_kind = (None if options.parameters_to_tune.noise_kind else
                        options.aggregate_params.noise_kind)
    selector = strategy_selector_factory.create(
        options.epsilon,
        options.delta,
        metric,
        is_public_partitions=public_partitions is not None)
    _attach_dp_strategies(candidates, options.aggregate_params,
                          fixed_noise_kind, selector)

    analysis_options = data_structures.UtilityAnalysisOptions(
        epsilon=options.epsilon,
        delta=options.delta,
        aggregate_params=options.aggregate_params,
        multi_param_configuration=candidates,
        partitions_sampling_prob=options.partitions_sampling_prob,
        pre_aggregated_data=options.pre_aggregated_data,
        use_device_sweep=options.use_device_sweep)
    reports, per_partition = utility_analysis.perform_utility_analysis(
        col, backend, analysis_options, data_extractors, public_partitions)

    reports.sort(key=lambda r: r.configuration_index)
    index_best = -1
    if options.aggregate_params.metrics:
        rmse = [r.metric_errors[0].absolute_error.rmse for r in reports]
        index_best = int(np.argmin(rmse))
    result = TuneResult(options=options,
                        contribution_histograms=contribution_histograms,
                        utility_analysis_parameters=candidates,
                        index_best=index_best,
                        utility_reports=reports)
    return result, per_partition


def _check_tune_args(options: TuneOptions, is_public_partitions: bool):
    metrics = options.aggregate_params.metrics
    if not metrics:
        if is_public_partitions:
            raise ValueError(
                "Empty metrics tunes partition selection, which is "
                "incompatible with public partitions.")
    elif len(metrics) > 1:
        raise ValueError(f"Tuning supports one metric; got {metrics}.")
    elif metrics[0] not in (Metrics.COUNT, Metrics.PRIVACY_ID_COUNT,
                            Metrics.SUM):
        raise ValueError("Tuning supports COUNT, PRIVACY_ID_COUNT and SUM; "
                         f"got {metrics[0]}.")
    if options.parameters_to_tune.min_sum_per_partition:
        raise ValueError("Tuning min_sum_per_partition is not supported.")
    if options.function_to_minimize != MinimizingFunction.ABSOLUTE_ERROR:
        raise NotImplementedError(
            f"Only {MinimizingFunction.ABSOLUTE_ERROR} is implemented.")
