"""Device-side (jitted JAX) multi-parameter utility-analysis sweep.

The reference evaluates a parameter sweep by building n_configurations
deep-copied combiner graphs and running every one of them against every row
(analysis/utility_analysis_engine.py:99-143). The host rewrite already
collapsed that to numpy grids (per_partition.py); this module puts the same
error model on the accelerator and keeps it there:

  * per-group metric values broadcast against a leading configuration axis,
    every [n_configs, n_partitions] error grid produced by batched
    segment-sums inside jit;
  * the cross-partition report reduction (cross_partition._metric_utility's
    weighted sums, including the per-partition nonlinearities rmse and
    relative errors) runs as a second device kernel over partition-size
    buckets, so a full UtilityReport sweep pulls only
    [n_buckets, n_fields, n_configs] scalars off the device — the
    [n_configs, n_partitions] grids are materialized to host numpy lazily
    and only if a consumer actually reads them.

The numpy implementation in per_partition.py / cross_partition.py remains
the conformance oracle; tests/analysis_test.py pins the two paths against
each other.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# [config-chunk, n_groups] float intermediates are bounded to roughly this
# many elements (the stacked segment-sum operand peaks at ~4x this, i.e.
# ~2 GB of f32 at this setting — well inside one v5e chip's HBM) so a
# wide sweep over tens of millions of groups never overflows device
# memory; configurations beyond the chunk run in further launches of the
# same compiled kernel. Sized so the 64-config benchmark sweep over 2M
# groups is ONE launch per metric: every extra launch pays a dispatch
# round trip, which dominates on tunneled links.
_CHUNK_ELEMENT_BUDGET = 1 << 27

# Order of the per-(config, bucket) report sums produced by _report_kernel.
# ABS/REL blocks mirror cross_partition._metric_utility's ValueErrors
# fields; the DROP block mirrors its DataDropInfo attribution.
ABS_FIELDS = ("exp_l0", "var_l0", "clip_min", "clip_max", "bias", "variance",
              "rmse", "rmse_dropped")
N_ABS = len(ABS_FIELDS)
N_REPORT_FIELDS = 2 * N_ABS + 4  # abs + rel + (raw, l0, linf, selection)

# The typed failure set of the device sweep path: backend import/
# initialization failures plus everything XLA raises at trace or execute
# time (XlaRuntimeError subclasses RuntimeError; device OOM surfaces as
# RuntimeError or MemoryError depending on the allocator). per_partition's
# auto-dispatch catches exactly these to fall back to the host path —
# anything outside this set is a bug, not a device limitation, and must
# propagate.
SWEEP_ERRORS = (ImportError, RuntimeError, ValueError, TypeError,
                MemoryError)


def _jnp():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def should_use_device(num_groups: int, n_configs: int) -> bool:
    """Auto-dispatch policy: accelerate when an accelerator exists and the
    grid is big enough to amortize the launch."""
    try:
        import jax
        backend = jax.default_backend()
    except SWEEP_ERRORS:  # pragma: no cover - jax always importable in-repo
        return False
    if backend == "cpu":
        return False
    return num_groups * max(n_configs, 1) >= (1 << 16)


@functools.lru_cache(maxsize=None)
def _kernels():
    """Builds the jitted kernels lazily so that importing the analysis
    package never initializes a JAX backend."""
    jax, jnp = _jnp()

    @functools.partial(jax.jit,
                       static_argnames=("n_partitions", "metric_kinds"))
    def metric_grids_multi(counts, sums, pk_ids, npart, lo, hi, l0,
                           n_partitions, metric_kinds):
        """All metrics' error grids in ONE dispatch.

        lo/hi: [n_metrics, C] per-metric clip bounds; l0: [C] (shared
        across metrics, so the keep-probability ratio q is computed
        once). Returns a tuple of (raw [P], grids [4, C, P]) per metric.
        Every launch saved is a dispatch round trip on tunneled links.
        """
        q = jnp.minimum(1.0, l0[:, None] / jnp.maximum(npart, 1.0)[None, :])
        outs = []
        for m, kind in enumerate(metric_kinds):
            if kind == "sum":
                v = sums
            elif kind == "count":
                v = counts
            else:  # privacy_id_count
                v = (counts > 0).astype(counts.dtype)
            vb = v[None, :]
            x = jnp.clip(vb, lo[m][:, None], hi[m][:, None])
            err = x - vb
            below = jnp.where(vb < lo[m][:, None], err, 0.0)
            above = jnp.where(vb > hi[m][:, None], err, 0.0)
            data = jnp.stack(
                [below, above, -x * (1.0 - q), x * x * q * (1.0 - q)])
            grids = jax.ops.segment_sum(jnp.moveaxis(data, -1, 0),
                                        pk_ids,
                                        num_segments=n_partitions)
            raw = jax.ops.segment_sum(v, pk_ids,
                                      num_segments=n_partitions)
            outs.append((raw, jnp.moveaxis(grids, 0, -1)))
        return tuple(outs)

    @functools.partial(jax.jit, static_argnames=("n_partitions",))
    def moment_grids(pk_ids, npart, l0, n_partitions):
        """[3, C, P] Poisson-binomial moment grids (mean, var, third
        central moment of the partition's surviving-unit count) feeding the
        refined-normal keep-probability approximation."""
        q = jnp.minimum(1.0, l0[:, None] / jnp.maximum(npart, 1.0)[None, :])
        data = jnp.stack([q, q * (1.0 - q), q * (1.0 - q) * (1.0 - 2.0 * q)])
        sums = jax.ops.segment_sum(jnp.moveaxis(data, -1, 0),
                                   pk_ids,
                                   num_segments=n_partitions)
        return jnp.moveaxis(sums, 0, -1)

    @functools.partial(jax.jit, static_argnames=("n_buckets",))
    def report_sums(raw, grids, std_noise, keep, bucket_ids, n_buckets):
        """[B, N_REPORT_FIELDS, C] cross-partition sums for one metric.

        Device twin of cross_partition._metric_utility's reductions: the
        per-partition nonlinearities (rmse, relative division by raw,
        dropped-mass attribution) are evaluated on-device and summed per
        partition-size bucket; the host divides by the weights and fills
        dataclasses. keep is [C, P] (ones for public partitions).
        """
        clip_min, clip_max, exp_l0, var_l0 = (grids[0], grids[1], grids[2],
                                              grids[3])
        rawb = jnp.broadcast_to(raw[None, :], exp_l0.shape)
        bias = exp_l0 + clip_min + clip_max
        variance = var_l0 + (std_noise * std_noise)[:, None]
        rmse = jnp.sqrt(bias * bias + variance)
        rmse_dropped = keep * rmse + (1.0 - keep) * jnp.abs(rawb)
        safe_raw = jnp.where(rawb == 0.0, 1.0, rawb)
        nz = (rawb != 0.0).astype(rmse.dtype)
        inv = nz / safe_raw
        inv2 = nz / (safe_raw * safe_raw)
        abs_fields = (exp_l0, var_l0, clip_min, clip_max, bias, variance,
                      rmse, rmse_dropped)
        rel_fields = (exp_l0 * inv, var_l0 * inv2, clip_min * inv,
                      clip_max * inv, bias * inv, variance * inv2,
                      rmse * inv, rmse_dropped * inv)
        l0_dropped = -exp_l0
        linf_dropped = clip_min - clip_max
        selection_dropped = (rawb - l0_dropped - linf_dropped) * (1.0 - keep)
        data = jnp.stack(
            [f * keep for f in abs_fields + rel_fields] +
            [rawb, l0_dropped, linf_dropped, selection_dropped])
        return jax.ops.segment_sum(jnp.moveaxis(data, -1, 0),
                                   bucket_ids,
                                   num_segments=n_buckets)

    @functools.partial(jax.jit, static_argnames=("n_buckets",))
    def keep_sums(keep, bucket_ids, n_buckets):
        """[B, 2, C]: (sum keep, sum keep*(1-keep)) per bucket — the
        kept-partitions Poisson-binomial mean/variance."""
        data = jnp.stack([keep, keep * (1.0 - keep)])
        return jax.ops.segment_sum(jnp.moveaxis(data, -1, 0),
                                   bucket_ids,
                                   num_segments=n_buckets)

    return moment_grids, report_sums, keep_sums, metric_grids_multi


# ---------------------------------------------------------------------------
# Mesh (multi-chip) kernels: the same math shard_map'ed over the device
# mesh. Groups shard over all mesh axes; the per-partition segment-sums
# produce full-width partials that ride the same ICI-first reduce-scatter
# as the aggregation kernels (parallel/sharded.py), leaving every grid
# sharded over the partition dimension. The report reduction then runs
# shard-local and psums its small [B, F, C] output.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _mesh_metric_kernel(mesh, padded_p: int, metric_kind: str):
    jax, jnp = _jnp()
    from jax.sharding import PartitionSpec as P
    from pipelinedp_tpu.parallel import sharded

    scatter_axes = sharded._scatter_axes(mesh)

    def local_step(counts, sums, pk_ids, npart, lo, hi, l0):
        if metric_kind == "sum":
            v = sums
        elif metric_kind == "count":
            v = counts
        else:  # privacy_id_count
            v = (counts > 0).astype(counts.dtype)
        vb = v[None, :]
        q = jnp.minimum(1.0, l0[:, None] / jnp.maximum(npart, 1.0)[None, :])
        x = jnp.clip(vb, lo[:, None], hi[:, None])
        err = x - vb
        below = jnp.where(vb < lo[:, None], err, 0.0)
        above = jnp.where(vb > hi[:, None], err, 0.0)
        data = jnp.stack(
            [below, above, -x * (1.0 - q), x * x * q * (1.0 - q)])
        # [P, 4, C] partials; padding groups carry pk == padded_p and drop.
        grids = jax.ops.segment_sum(jnp.moveaxis(data, -1, 0), pk_ids,
                                    num_segments=padded_p)
        raw = jax.ops.segment_sum(v, pk_ids, num_segments=padded_p)
        return (sharded._reduce_scatter(raw, scatter_axes),
                sharded._reduce_scatter(grids, scatter_axes))

    fn = sharded.shard_map(local_step,
                       mesh=mesh,
                       in_specs=(sharded._spec(mesh),) * 4 + (P(),) * 3,
                       out_specs=(sharded._part_spec(mesh),) * 2,
                       check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _mesh_moment_kernel(mesh, padded_p: int):
    jax, jnp = _jnp()
    from jax.sharding import PartitionSpec as P
    from pipelinedp_tpu.parallel import sharded

    scatter_axes = sharded._scatter_axes(mesh)

    def local_step(pk_ids, npart, l0):
        q = jnp.minimum(1.0, l0[:, None] / jnp.maximum(npart, 1.0)[None, :])
        data = jnp.stack([q, q * (1.0 - q), q * (1.0 - q) * (1.0 - 2.0 * q)])
        sums = jax.ops.segment_sum(jnp.moveaxis(data, -1, 0), pk_ids,
                                   num_segments=padded_p)  # [P, 3, C]
        return sharded._reduce_scatter(sums, scatter_axes)

    fn = sharded.shard_map(local_step,
                       mesh=mesh,
                       in_specs=(sharded._spec(mesh),) * 2 + (P(),),
                       out_specs=sharded._part_spec(mesh),
                       check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _mesh_report_kernel(mesh, n_buckets_p1: int, with_keep_sums: bool):
    jax, jnp = _jnp()
    from jax.sharding import PartitionSpec as P
    from pipelinedp_tpu.parallel import sharded

    all_axes = tuple(mesh.axis_names)

    def local_step(raw, grids, std_noise, keep, bucket_ids):
        # Shard-local layout: raw [P_l], grids [P_l, 4, C], keep [P_l, C]
        # (pre-transposed on host), bucket_ids [P_l]. Same field math as
        # the single-device report_sums, partition-major.
        clip_min, clip_max = grids[:, 0], grids[:, 1]
        exp_l0, var_l0 = grids[:, 2], grids[:, 3]
        rawb = jnp.broadcast_to(raw[:, None], exp_l0.shape)
        bias = exp_l0 + clip_min + clip_max
        variance = var_l0 + (std_noise * std_noise)[None, :]
        rmse = jnp.sqrt(bias * bias + variance)
        rmse_dropped = keep * rmse + (1.0 - keep) * jnp.abs(rawb)
        safe_raw = jnp.where(rawb == 0.0, 1.0, rawb)
        nz = (rawb != 0.0).astype(rmse.dtype)
        inv = nz / safe_raw
        inv2 = nz / (safe_raw * safe_raw)
        abs_fields = (exp_l0, var_l0, clip_min, clip_max, bias, variance,
                      rmse, rmse_dropped)
        rel_fields = (exp_l0 * inv, var_l0 * inv2, clip_min * inv,
                      clip_max * inv, bias * inv, variance * inv2,
                      rmse * inv, rmse_dropped * inv)
        l0_dropped = -exp_l0
        linf_dropped = clip_min - clip_max
        selection_dropped = (rawb - l0_dropped - linf_dropped) * (1.0 - keep)
        data = jnp.stack(
            [f * keep for f in abs_fields + rel_fields] +
            [rawb, l0_dropped, linf_dropped, selection_dropped])  # [F, P, C]
        sums = jax.ops.segment_sum(jnp.moveaxis(data, 1, 0), bucket_ids,
                                   num_segments=n_buckets_p1)
        for axis in all_axes:
            sums = jax.lax.psum(sums, axis)
        if not with_keep_sums:
            return sums
        kdata = jnp.stack([keep, keep * (1.0 - keep)])  # [2, P, C]
        ksums = jax.ops.segment_sum(jnp.moveaxis(kdata, 1, 0), bucket_ids,
                                    num_segments=n_buckets_p1)
        for axis in all_axes:
            ksums = jax.lax.psum(ksums, axis)
        return sums, ksums

    part = sharded._part_spec(mesh)
    fn = sharded.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(part, part, P(), part, part),
        out_specs=(P(), P()) if with_keep_sums else P(),
        check_vma=False)
    return jax.jit(fn)


@dataclasses.dataclass
class _MetricGrids:
    """Device-resident error grids of one metric."""
    raw: object  # [P] device array
    grids: object  # [4, C, P] device array
    std_noise: np.ndarray  # [C] host
    metric_kind: str


class DeviceSweep:
    """Device-resident state of one utility-analysis sweep.

    Uploads the pre-aggregate columns once, computes per-metric error grids
    (kept on device), and serves both consumers: lazy host materialization
    of the [C, P] grids and the fused cross-partition report reduction.
    """

    def __init__(self, pk_ids: np.ndarray, counts: np.ndarray,
                 sums: np.ndarray, npart: np.ndarray, n_partitions: int,
                 n_configs: int, mesh=None):
        jax, jnp = _jnp()
        self.n_partitions = n_partitions
        self.n_configs = n_configs
        self.n_groups = len(pk_ids)
        self._mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding
            from pipelinedp_tpu.parallel import sharded
            self._padded_p = sharded.padded_num_partitions(
                mesh, max(n_partitions, 1))
            n_dev = mesh.devices.size
            g = len(pk_ids)
            g_pad = ((g + n_dev - 1) // n_dev) * n_dev if g else n_dev
            # Padding groups point at the out-of-range partition id
            # padded_p, which segment_sum drops.
            def pad(a, dtype, fill):
                out = np.full(g_pad, fill, dtype=dtype)
                out[:g] = np.asarray(a, dtype=dtype)
                return out
            row_sharding = NamedSharding(mesh, sharded._spec(mesh))
            self._counts = jax.device_put(pad(counts, np.float32, 0.0),
                                          row_sharding)
            self._sums = jax.device_put(pad(sums, np.float32, 0.0),
                                        row_sharding)
            self._pk_ids = jax.device_put(
                pad(pk_ids, np.int32, self._padded_p), row_sharding)
            self._npart = jax.device_put(pad(npart, np.float32, 1.0),
                                         row_sharding)
        else:
            self._padded_p = n_partitions
            self._counts = jnp.asarray(np.asarray(counts, dtype=np.float32))
            self._sums = jnp.asarray(np.asarray(sums, dtype=np.float32))
            self._pk_ids = jnp.asarray(np.asarray(pk_ids, dtype=np.int32))
            self._npart = jnp.asarray(np.asarray(npart, dtype=np.float32))
        self.metrics: List[_MetricGrids] = []
        self._moments = None  # [3, C, P] device array when computed
        # Exact (float64, host) per-partition raw values of the first
        # metric, for report-size bucketing; set by the builder
        # (per_partition._build_device_sweep). The device raw is float32
        # and could straddle a 1-2-5 bucket boundary.
        self.exact_sizes: Optional[np.ndarray] = None
        self._lazy_views: List["LazyMetricErrorArrays"] = []

    def _config_chunk(self, per_config_elements: int) -> int:
        return max(
            1,
            min(self.n_configs,
                _CHUNK_ELEMENT_BUDGET // max(per_config_elements, 1)))

    def add_metric(self, metric_kind: str, lo: np.ndarray, hi: np.ndarray,
                   l0: np.ndarray, std_noise: np.ndarray) -> int:
        """Computes one metric's error grids on device; returns its index.

        metric_kind: "sum" | "count" | "privacy_id_count".
        """
        _, jnp = _jnp()
        if self._mesh is not None:
            kernel = _mesh_metric_kernel(self._mesh, self._padded_p,
                                         metric_kind)
            n_dev = self._mesh.devices.size
            step = self._config_chunk(max(self.n_groups // n_dev, 1))
            grid_axis = 2  # mesh layout is [P, 4, C]
        else:
            # The single-metric case IS the 1-tuple case of the fused
            # kernel — one error-model body to maintain per backend.
            kernel = _kernels()[3]
            step = self._config_chunk(self.n_groups)
            grid_axis = 1
        raw = None
        parts = []
        for s in range(0, self.n_configs, step):
            e = min(s + step, self.n_configs)
            clo = jnp.asarray(np.asarray(lo[s:e], dtype=np.float32))
            chi = jnp.asarray(np.asarray(hi[s:e], dtype=np.float32))
            cl0 = jnp.asarray(np.asarray(l0[s:e], dtype=np.float32))
            if self._mesh is not None:
                r, grids = kernel(self._counts, self._sums, self._pk_ids,
                                  self._npart, clo, chi, cl0)
            else:
                ((r, grids),) = kernel(self._counts, self._sums,
                                       self._pk_ids, self._npart,
                                       clo[None, :], chi[None, :], cl0,
                                       n_partitions=self.n_partitions,
                                       metric_kinds=(metric_kind,))
            if raw is None:
                raw = r
            parts.append(grids)
        grids = parts[0] if len(parts) == 1 else jnp.concatenate(
            parts, axis=grid_axis)
        self.metrics.append(
            _MetricGrids(raw=raw,
                         grids=grids,
                         std_noise=np.asarray(std_noise, dtype=np.float64),
                         metric_kind=metric_kind))
        return len(self.metrics) - 1

    def add_metrics(self, metric_kinds, los, his, l0,
                    std_noises) -> List[int]:
        """All metrics' error grids in one device dispatch (single-device
        path; the mesh path runs per-metric kernels). Equivalent to
        calling add_metric per metric — pinned by tests — but pays one
        launch round trip instead of len(metrics), and computes the shared
        keep-probability ratio once."""
        if self._mesh is not None or not metric_kinds:
            return [
                self.add_metric(kind, lo, hi, l0, std)
                for kind, lo, hi, std in zip(metric_kinds, los, his,
                                             std_noises)
            ]
        _, jnp = _jnp()
        kernel = _kernels()[3]
        # Chunk by the FUSED footprint — the single-metric element count
        # times the metric count. XLA's buffer assignment usually reuses
        # the big [4, C, G] intermediates between the kernel's
        # data-independent metric blocks, but the admitted worst case (no
        # reuse) is len(metric_kinds) x the single-metric peak, which
        # OOMed smaller-HBM accelerators when chunking ignored the metric
        # count. Dividing the budget by len(metric_kinds) keeps the
        # worst case inside the same envelope as add_metric.
        step = self._config_chunk(self.n_groups * len(metric_kinds))
        parts = [[] for _ in metric_kinds]
        raws = [None] * len(metric_kinds)
        lo_arr = np.asarray(los, dtype=np.float32)
        hi_arr = np.asarray(his, dtype=np.float32)
        for s in range(0, self.n_configs, step):
            e = min(s + step, self.n_configs)
            outs = kernel(self._counts, self._sums, self._pk_ids,
                          self._npart, jnp.asarray(lo_arr[:, s:e]),
                          jnp.asarray(hi_arr[:, s:e]),
                          jnp.asarray(np.asarray(l0[s:e],
                                                 dtype=np.float32)),
                          n_partitions=self.n_partitions,
                          metric_kinds=tuple(metric_kinds))
            for m, (r, grids) in enumerate(outs):
                if raws[m] is None:
                    raws[m] = r
                parts[m].append(grids)
        indices = []
        for m, kind in enumerate(metric_kinds):
            grids = (parts[m][0] if len(parts[m]) == 1 else
                     jnp.concatenate(parts[m], axis=1))
            self.metrics.append(
                _MetricGrids(raw=raws[m],
                             grids=grids,
                             std_noise=np.asarray(std_noises[m],
                                                  dtype=np.float64),
                             metric_kind=kind))
            indices.append(len(self.metrics) - 1)
        return indices

    def materialize_metric(self, index: int) -> Dict[str, np.ndarray]:
        """Pulls one metric's grids to host numpy (float64), in the
        MetricErrorArrays field layout."""
        m = self.metrics[index]
        if m.grids is None:
            raise RuntimeError(
                "DeviceSweep.release(materialize=False) already dropped the "
                "device grids; materialize before releasing to keep "
                "per-partition access working.")
        grids = np.asarray(m.grids, dtype=np.float64)
        if self._mesh is not None:
            # Mesh layout is [P_pad, 4, C]: transpose and trim the padding.
            grids = np.transpose(grids, (1, 2, 0))[:, :, :self.n_partitions]
        raw = self.pull_raw(index)
        return {
            "raw": np.broadcast_to(raw,
                                   (self.n_configs,
                                    self.n_partitions)).copy(),
            "clip_min_err": grids[0],
            "clip_max_err": grids[1],
            "exp_l0_err": grids[2],
            "var_l0_err": grids[3],
        }

    def pull_raw(self, index: int) -> np.ndarray:
        """[P] raw per-partition values of one metric (host float64)."""
        raw = np.asarray(self.metrics[index].raw, dtype=np.float64)
        return raw[:self.n_partitions]

    def compute_moments(self, l0: np.ndarray) -> None:
        """Computes the [3, C, P] keep-probability moment grids on device
        (configurations sharing an L0 bound share the kernel work)."""
        _, jnp = _jnp()
        l0 = np.asarray(l0, dtype=np.float32)
        uniq, inverse = np.unique(l0, return_inverse=True)
        if self._mesh is not None:
            kernel = _mesh_moment_kernel(self._mesh, self._padded_p)
            n_dev = self._mesh.devices.size
            step = self._config_chunk(max(self.n_groups // n_dev, 1))
            cfg_axis = 2  # [P, 3, C]
        else:
            kernel = _kernels()[0]
            step = self._config_chunk(self.n_groups)
            cfg_axis = 1
        parts = []
        for s in range(0, len(uniq), step):
            e = min(s + step, len(uniq))
            if self._mesh is not None:
                parts.append(
                    kernel(self._pk_ids, self._npart, jnp.asarray(uniq[s:e])))
            else:
                parts.append(
                    kernel(self._pk_ids, self._npart, jnp.asarray(uniq[s:e]),
                           n_partitions=self.n_partitions))
        grids = parts[0] if len(parts) == 1 else jnp.concatenate(
            parts, axis=cfg_axis)
        self._moments = jnp.take(grids, jnp.asarray(inverse), axis=cfg_axis)

    def pull_moments(self) -> Optional[np.ndarray]:
        if self._moments is None:
            return None
        moments = np.asarray(self._moments, dtype=np.float64)
        if self._mesh is not None:
            moments = np.transpose(moments,
                                   (1, 2, 0))[:, :, :self.n_partitions]
        return moments

    def drop_inputs(self) -> None:
        """Frees the uploaded input columns and the moments grid — called
        by the builder once all kernels have run; only the per-metric
        grids (lazy host materialization, report reduction) stay
        resident."""
        self._counts = self._sums = self._pk_ids = self._npart = None
        self._moments = None

    def release(self, materialize: bool = True) -> None:
        """Frees the device-resident grids (HBM held otherwise lives as
        long as the analysis result).

        materialize=True first pulls every metric's grids into its lazy
        host views so per-partition consumers keep working; False drops
        the device data outright (subsequent lazy access raises).
        """
        if materialize:
            for view in self._lazy_views:
                view.raw  # touch: materializes all grid fields
        for m in self.metrics:
            m.raw = None
            m.grids = None
        self.drop_inputs()

    def report_sums(
            self, bucket_ids: np.ndarray, n_buckets: int,
            keep_prob: Optional[np.ndarray]
    ) -> Tuple[List[np.ndarray], Optional[np.ndarray]]:
        """Fused cross-partition reduction.

        Returns (per-metric [B, N_REPORT_FIELDS, C] sums,
        [B, 2, C] keep sums or None for public partitions). Only these
        small arrays leave the device.
        """
        jax, jnp = _jnp()
        if self._mesh is not None:
            return self._report_sums_mesh(bucket_ids, n_buckets, keep_prob)
        report_kernel, keep_kernel = _kernels()[1:3]
        dbuckets = jnp.asarray(np.asarray(bucket_ids, dtype=np.int32))
        if keep_prob is None:
            dkeep = jnp.ones((self.n_configs, self.n_partitions),
                             dtype=jnp.float32)
        else:
            dkeep = jnp.asarray(np.asarray(keep_prob, dtype=np.float32))
        step = self._config_chunk(self.n_partitions * N_REPORT_FIELDS)
        metric_sums = []
        for m in self.metrics:
            parts = []
            for s in range(0, self.n_configs, step):
                e = min(s + step, self.n_configs)
                parts.append(
                    report_kernel(m.raw, m.grids[:, s:e],
                                  jnp.asarray(
                                      m.std_noise[s:e].astype(np.float32)),
                                  dkeep[s:e], dbuckets,
                                  n_buckets=n_buckets))
            sums = (parts[0] if len(parts) == 1 else jnp.concatenate(
                parts, axis=2))
            metric_sums.append(np.asarray(sums, dtype=np.float64))
        ksums = None
        if keep_prob is not None:
            ksums = np.asarray(keep_kernel(dkeep, dbuckets,
                                           n_buckets=n_buckets),
                               dtype=np.float64)
        return metric_sums, ksums

    def _report_sums_mesh(self, bucket_ids, n_buckets, keep_prob):
        """Mesh twin of report_sums: per-shard bucket reductions + psum.

        Padding partitions carry the extra bucket id n_buckets and zero
        keep probability; the extra bucket row is trimmed before return.
        """
        jax, jnp = _jnp()
        from jax.sharding import NamedSharding
        from pipelinedp_tpu.parallel import sharded

        pad_p = self._padded_p
        part_sharding = NamedSharding(self._mesh,
                                      sharded._part_spec(self._mesh))
        buckets_padded = np.full(pad_p, n_buckets, dtype=np.int32)
        buckets_padded[:self.n_partitions] = np.asarray(bucket_ids,
                                                        dtype=np.int32)
        dbuckets = jax.device_put(buckets_padded, part_sharding)
        keep_pc = np.zeros((pad_p, self.n_configs), dtype=np.float32)
        if keep_prob is None:
            keep_pc[:self.n_partitions, :] = 1.0
        else:
            keep_pc[:self.n_partitions, :] = np.asarray(
                keep_prob, dtype=np.float32).T
        with_keep = keep_prob is not None
        kernel = _mesh_report_kernel(self._mesh, n_buckets + 1, with_keep)
        metric_sums = []
        ksums = None
        dkeep = jax.device_put(keep_pc, part_sharding)
        for i, m in enumerate(self.metrics):
            out = kernel(m.raw, m.grids,
                         jnp.asarray(m.std_noise.astype(np.float32)), dkeep,
                         dbuckets)
            if with_keep:
                sums, ks = out
                if i == 0:
                    ksums = np.asarray(ks, dtype=np.float64)[:n_buckets]
            else:
                sums = out
            metric_sums.append(
                np.asarray(sums, dtype=np.float64)[:n_buckets])
        return metric_sums, ksums


class LazyMetricErrorArrays:
    """MetricErrorArrays twin whose [C, P] grids materialize from the
    device on first attribute access (per_partition.MetricErrorArrays is
    the eager host equivalent)."""

    _GRID_FIELDS = ("raw", "clip_min_err", "clip_max_err", "exp_l0_err",
                    "var_l0_err")

    def __init__(self, metric, std_noise, noise_kind, sweep: DeviceSweep,
                 index: int):
        self.metric = metric
        self.std_noise = std_noise
        self.noise_kind = noise_kind
        self._sweep = sweep
        self._index = index
        sweep._lazy_views.append(self)

    def __getattr__(self, name):
        if name in LazyMetricErrorArrays._GRID_FIELDS:
            self.__dict__.update(
                self._sweep.materialize_metric(self._index))
            return self.__dict__[name]
        raise AttributeError(name)
