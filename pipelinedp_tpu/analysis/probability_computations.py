"""Probability computations for sums of noise distributions.

Parity: /root/reference/analysis/probability_computations.py:20-35, which
estimates quantiles of (Laplace + Gaussian) by Monte Carlo because "exact
formulas ... turned out too slow" in per-row Python. Here the exact CDF is
the default: it is a closed form in Phi (derived below), evaluated in the
log domain for stability and inverted by vectorized bisection — thousands
of quantiles per millisecond, no sampling error. The Monte-Carlo method is
kept for cross-checking.

Derivation (Z = G + L, G ~ N(0, sigma^2), L ~ Laplace(b)): conditioning on
G and using E[e^{tG} 1{G <= z}] = e^{t^2 sigma^2 / 2} Phi(z/sigma - t sigma),

  P(Z <= z) = Phi(z/sigma)
              - 1/2 exp(sigma^2/(2b^2) - z/b) Phi(z/sigma - sigma/b)
              + 1/2 exp(sigma^2/(2b^2) + z/b) Phi(-z/sigma - sigma/b)
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np
from scipy import special
from scipy import stats


def _log_ndtr(x: np.ndarray) -> np.ndarray:
    return special.log_ndtr(x)


def _sum_cdf(z: np.ndarray, b: float, sigma: float) -> np.ndarray:
    """CDF of Laplace(b) + N(0, sigma^2), elementwise, stable for all z."""
    z = np.asarray(z, dtype=np.float64)
    if sigma == 0:
        return stats.laplace.cdf(z, scale=b)
    if b == 0:
        return special.ndtr(z / sigma)
    u = z / sigma
    r = sigma / b
    # Each exp(...) * Phi(...) product evaluated as exp(log-sum): the
    # exponentials overflow individually for |z| >> b while the products
    # stay in [0, 1].
    t1 = 0.5 * np.exp(r * r / 2 - z / b + _log_ndtr(u - r))
    t2 = 0.5 * np.exp(r * r / 2 + z / b + _log_ndtr(-u - r))
    return special.ndtr(u) - t1 + t2


def compute_sum_laplace_gaussian_quantiles(
        laplace_b: float,
        gaussian_sigma: float,
        quantiles: Sequence[float],
        num_samples: int = 10**4,
        method: str = "exact",
        rng: Optional[np.random.Generator] = None) -> List[float]:
    """Quantiles of the sum of independent Laplace and Gaussian noise.

    method="exact" (default) inverts the closed-form CDF by vectorized
    bisection; method="monte_carlo" reproduces the reference's estimator
    (num_samples draws). Signature superset of the reference's
    (probability_computations.py:20).
    """
    qs = np.asarray(quantiles, dtype=np.float64)
    if method == "monte_carlo":
        rng = rng or np.random.default_rng()
        samples = rng.laplace(scale=laplace_b, size=num_samples)
        if gaussian_sigma:
            samples = samples + rng.normal(0, gaussian_sigma,
                                           size=num_samples)
        return list(np.quantile(samples, qs))
    if method != "exact":
        raise ValueError(f"Unknown method {method!r}")
    if laplace_b == 0 and gaussian_sigma == 0:
        return [0.0] * len(qs)
    # Bracket from the MOST extreme requested level, in closed form so no
    # ppf can overflow to inf: |laplace quantile at level e| = b ln(1/(2e)),
    # |gaussian quantile| <= sigma sqrt(2 ln(1/e)); their sum bounds the sum
    # distribution's quantile. Levels at/below float resolution are clamped
    # (the exact 0/1 quantiles are infinite).
    eps_min = float(np.min(np.minimum(qs, 1.0 - qs)))
    eps_min = min(max(eps_min, 1e-300), 0.5)
    log_term = math.log(1.0 / eps_min)
    span = (laplace_b * max(log_term - math.log(2.0), 0.0) +
            gaussian_sigma * math.sqrt(2.0 * log_term) + 1.0)
    lo = np.full(len(qs), -span)
    hi = np.full(len(qs), span)
    for _ in range(80):  # 2^-80 * span: far below float64 resolution
        mid = 0.5 * (lo + hi)
        below = _sum_cdf(mid, laplace_b, gaussian_sigma) < qs
        lo = np.where(below, mid, lo)
        hi = np.where(below, hi, mid)
    return list(0.5 * (lo + hi))
