"""Native secure-noise library: build, load, install into noise_core."""

from pipelinedp_tpu.native.loader import (install, is_loaded, load)

__all__ = ["install", "is_loaded", "load"]
