"""Loader for the native secure-noise library.

Builds `secure_noise.cc` into a shared object on first use (plain
`g++ -O2 -shared`; no external build deps), loads it with ctypes, and
installs the discrete samplers as noise_core's `sample_laplace` /
`sample_gaussian` implementations. When no compiler or writable cache is
available, the numpy granularity-snapping fallback in noise_core stays in
place (distributionally equivalent; without the bit-exact discrete
construction).

Python <-> C++ boundary: ctypes over a 3-function C ABI (the environment
has no pybind11 — see the repo build notes). The samplers return *integer*
noise in granularity units; scaling by the power-of-two granularity happens
here, which is exact in float64.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_EXT = sysconfig.get_config_var("EXT_SUFFIX") or ".so"

_lock = threading.Lock()
_libs: dict = {}  # stem -> CDLL | None (None = load failed)

# The error types a native build/load can surface; callers that treat the
# native path as an optimization catch exactly these (ops/streaming.py) —
# never a bare Exception, which would also swallow NativeRequiredError.
LOADER_ERRORS = (OSError, subprocess.SubprocessError, AttributeError)

# When set to a truthy value ("1"/"true"/"yes"), a failed native
# build/load raises NativeRequiredError instead of silently installing
# the numpy fallback — CI and prod set it so a toolchain regression is a
# hard error, not a quiet 10x slowdown.
REQUIRE_NATIVE_ENV = "PIPELINEDP_TPU_REQUIRE_NATIVE"


class NativeRequiredError(RuntimeError):
    """Native library unavailable while REQUIRE_NATIVE_ENV demands it."""


# Worker-pool width for the native encode (pdp_pack_buckets,
# pdp_rle_sort_range, pdp_rle_emit_range): 0 = auto (hardware
# concurrency, capped at 16 in the C++), 1..64 forces the width. Output
# is bit-identical at every width (disjoint buckets per worker); the knob
# only trades host wall time — see README "Tuning knobs".
ENCODE_THREADS_ENV = "PIPELINEDP_TPU_ENCODE_THREADS"


def env_int(name: str, default: int, lo: int, hi: int) -> int:
    """Validated integer env knob: unset/empty -> default; junk or
    out-of-range values raise instead of silently running misconfigured."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        value = int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}") from None
    if not lo <= value <= hi:
        raise ValueError(
            f"{name} must be in [{lo}, {hi}], got {value}")
    return value


def encode_threads() -> int:
    """The validated PIPELINEDP_TPU_ENCODE_THREADS value (0 = auto)."""
    return env_int(ENCODE_THREADS_ENV, 0, 0, 64)


def apply_encode_threads(lib) -> int:
    """Pushes the env-configured worker-pool width into the native
    library (re-read per call so tests can flip the env between
    encodes). Returns the applied value."""
    n = encode_threads()
    if lib is not None and hasattr(lib, "pdp_set_encode_threads"):
        lib.pdp_set_encode_threads(n)
    return n


def _native_required() -> bool:
    return os.environ.get(REQUIRE_NATIVE_ENV,
                          "").strip().lower() in ("1", "true", "yes")


def _build(stem: str) -> bool:
    src = os.path.join(_DIR, f"{stem}.cc")
    so = os.path.join(_DIR, f"_{stem}{_EXT}")
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread", src,
        "-o", so
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logging.info("pipelinedp_tpu.native: build of %s failed (%s); "
                     "using the numpy fallback", stem, e)
        return False


def _load_lib(stem: str, abi_symbol: str,
              abi_version: int = 1) -> Optional[ctypes.CDLL]:
    """Builds (if stale/missing) and loads native/<stem>.cc; caches.

    Under REQUIRE_NATIVE_ENV a failure raises NativeRequiredError instead
    of returning None (checked on cache hits too, so a permissive early
    call can't mask a later strict one).
    """
    with _lock:
        if stem in _libs:
            lib = _libs[stem]
            if lib is None and _native_required():
                raise NativeRequiredError(
                    f"native library '{stem}' failed to build/load and "
                    f"{REQUIRE_NATIVE_ENV} is set")
            return lib
        src = os.path.join(_DIR, f"{stem}.cc")
        so = os.path.join(_DIR, f"_{stem}{_EXT}")
        if not os.path.exists(so) or (os.path.exists(src) and
                                      os.path.getmtime(so) <
                                      os.path.getmtime(src)):
            if not _build(stem):
                _libs[stem] = None
                if _native_required():
                    raise NativeRequiredError(
                        f"native library '{stem}' failed to build and "
                        f"{REQUIRE_NATIVE_ENV} is set")
                return None
        lib = _try_load(so, abi_symbol, abi_version)
        if lib is None and os.path.exists(src):
            # A stale prebuilt .so can pass the mtime check (archive
            # extraction and docker COPY normalize mtimes) yet miss the
            # current ABI; rebuild from source once before giving up.
            logging.info(
                "pipelinedp_tpu.native: %s failed to load; rebuilding "
                "from source", stem)
            if _build(stem):
                lib = _try_load(so, abi_symbol, abi_version)
        _libs[stem] = lib
        if lib is None and _native_required():
            raise NativeRequiredError(
                f"native library '{stem}' failed to load and "
                f"{REQUIRE_NATIVE_ENV} is set")
        return lib


def _try_load(so: str, abi_symbol: str,
              abi_version: int) -> Optional[ctypes.CDLL]:
    try:
        lib = ctypes.CDLL(so)
        abi = getattr(lib, abi_symbol)
        abi.restype = ctypes.c_int
        if abi() != abi_version:
            raise OSError(f"ABI version mismatch (want {abi_version}, "
                          f"got {abi()})")
        return lib
    except OSError as e:
        logging.info("pipelinedp_tpu.native: load of %s failed (%s)", so, e)
        return None


def load() -> Optional[ctypes.CDLL]:
    """The secure-noise library, building it if needed; None on failure."""
    lib = _load_lib("secure_noise", "pdp_noise_abi_version", abi_version=2)
    if lib is not None and not getattr(lib, "_pdp_typed", False):
        for name in ("pdp_sample_discrete_laplace",
                     "pdp_sample_discrete_gaussian"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.c_double
            ]
        fn = lib.pdp_sample_uniform_double
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
        lib._pdp_typed = True
    return lib


def load_row_packer() -> Optional[ctypes.CDLL]:
    """The row bucketing/packing library; None on failure."""
    lib = _load_lib("row_packer", "pdp_row_packer_abi_version",
                    abi_version=7)
    if lib is not None and not getattr(lib, "_pdp_typed", False):
        fn = lib.pdp_set_encode_threads
        fn.restype = None
        fn.argtypes = [ctypes.c_int]
        fn = lib.pdp_get_encode_threads
        fn.restype = ctypes.c_int
        fn.argtypes = []
        fn = lib.pdp_rle_prep
        fn.restype = ctypes.c_void_p
        fn.argtypes = [
            ctypes.POINTER(ctypes.c_int32),  # pid
            ctypes.POINTER(ctypes.c_int32),  # pk
            ctypes.c_void_p,  # value (float* or NULL)
            ctypes.POINTER(ctypes.c_int32),  # vidx (or NULL => inline)
            ctypes.c_double,  # v_lo
            ctypes.c_double,  # v_scale
            ctypes.c_int64,  # n
            ctypes.c_int32,  # pid_lo
            ctypes.c_int64,  # k buckets
            ctypes.c_int,  # value_mode
            ctypes.c_int64,  # pid_span (for exact entry counting)
            ctypes.POINTER(ctypes.c_int64),  # n_entries out (or NULL)
            ctypes.POINTER(ctypes.c_int64),  # n_rows out
            ctypes.POINTER(ctypes.c_int64),  # stats out [fail, max_idx]
        ]
        fn = lib.pdp_rle_sort_range
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.c_void_p,  # handle
            ctypes.c_int64,  # b0
            ctypes.c_int64,  # b1
            ctypes.POINTER(ctypes.c_int64),  # n_uniq out
        ]
        fn = lib.pdp_rle_emit_range
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.c_void_p,  # handle
            ctypes.c_int64,  # b0
            ctypes.c_int64,  # b1
            ctypes.c_int,  # pid_mode (0 RLE, 1 unsorted bit-planes)
            ctypes.c_int,  # bytes_pid
            ctypes.c_int,  # bits_pid (planes mode)
            ctypes.c_int,  # bits_pk
            ctypes.c_int,  # bits_val
            ctypes.c_int64,  # cap
            ctypes.c_int64,  # ucap
            ctypes.POINTER(ctypes.c_uint8),  # out slab rows
            ctypes.c_int64,  # width
        ]
        fn = lib.pdp_rle_free
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p]
        fn = lib.pdp_pack_buckets
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.POINTER(ctypes.c_int32),  # pid
            ctypes.POINTER(ctypes.c_int32),  # pk
            ctypes.c_void_p,  # value (float* or NULL)
            ctypes.c_int64,  # n
            ctypes.c_int32,  # pid_lo
            ctypes.c_int64,  # n_buckets
            ctypes.c_int,  # bytes_pid
            ctypes.c_int,  # bytes_pk
            ctypes.c_int,  # value_f16
            ctypes.POINTER(ctypes.c_uint8),  # out
            ctypes.c_int64,  # cap
            ctypes.POINTER(ctypes.c_int64),  # counts
        ]
        lib._pdp_typed = True
    if lib is not None:
        # Re-applied on every load call (the CDLL itself is cached) so an
        # env change between encodes takes effect immediately.
        apply_encode_threads(lib)
    return lib


def is_loaded() -> bool:
    return _libs.get("secure_noise") is not None


def _sample(fn, units: float, size) -> np.ndarray:
    n = 1 if size is None else int(np.prod(size))
    out = np.empty(max(n, 1), dtype=np.int64)
    rc = fn(out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            float(units))
    if rc != 0:
        raise ValueError(f"native sampler rejected parameters (units="
                         f"{units})")
    return out[:n]


def install() -> bool:
    """Loads the library and installs the native samplers into noise_core.

    Returns True when the native path is active.
    """
    lib = load()
    if lib is None:
        return False
    from pipelinedp_tpu import noise_core

    def native_laplace(scale: float, size=None):
        g = noise_core.laplace_granularity(scale)
        ints = _sample(lib.pdp_sample_discrete_laplace, scale / g, size)
        noise = ints.astype(np.float64) * g
        return float(noise[0]) if size is None else noise.reshape(size)

    def native_gaussian(stddev: float, size=None):
        g = noise_core.gaussian_granularity(stddev)
        ints = _sample(lib.pdp_sample_discrete_gaussian, stddev / g, size)
        noise = ints.astype(np.float64) * g
        return float(noise[0]) if size is None else noise.reshape(size)

    def native_uniform(size=None):
        n = 1 if size is None else int(np.prod(size))
        out = np.empty(max(n, 1), dtype=np.float64)
        rc = lib.pdp_sample_uniform_double(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n)
        if rc != 0:
            raise ValueError("native uniform sampler failed")
        return float(out[0]) if size is None else out[:n].reshape(size)

    noise_core.sample_laplace = native_laplace
    noise_core.sample_gaussian = native_gaussian
    noise_core.sample_uniform = native_uniform
    return True
