"""Loader for the native secure-noise library.

Builds `secure_noise.cc` into a shared object on first use (plain
`g++ -O2 -shared`; no external build deps), loads it with ctypes, and
installs the discrete samplers as noise_core's `sample_laplace` /
`sample_gaussian` implementations. When no compiler or writable cache is
available, the numpy granularity-snapping fallback in noise_core stays in
place (distributionally equivalent; without the bit-exact discrete
construction).

Python <-> C++ boundary: ctypes over a 3-function C ABI (the environment
has no pybind11 — see the repo build notes). The samplers return *integer*
noise in granularity units; scaling by the power-of-two granularity happens
here, which is exact in float64.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "secure_noise.cc")
_SO = os.path.join(_DIR, f"_secure_noise{sysconfig.get_config_var('EXT_SUFFIX') or '.so'}")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread", _SRC,
        "-o", _SO
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logging.info("pipelinedp_tpu.native: build failed (%s); using the "
                     "numpy fallback sampler", e)
        return False


def load() -> Optional[ctypes.CDLL]:
    """Returns the loaded library, building it if needed; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO) or (os.path.exists(_SRC) and
                                       os.path.getmtime(_SO) <
                                       os.path.getmtime(_SRC)):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.pdp_noise_abi_version.restype = ctypes.c_int
            if lib.pdp_noise_abi_version() != 1:
                raise OSError("ABI version mismatch")
            for name in ("pdp_sample_discrete_laplace",
                         "pdp_sample_discrete_gaussian"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
                fn.argtypes = [
                    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                    ctypes.c_double
                ]
            _lib = lib
        except OSError as e:
            logging.info("pipelinedp_tpu.native: load failed (%s)", e)
            _load_failed = True
        return _lib


def is_loaded() -> bool:
    return _lib is not None


def _sample(fn, units: float, size) -> np.ndarray:
    n = 1 if size is None else int(np.prod(size))
    out = np.empty(max(n, 1), dtype=np.int64)
    rc = fn(out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            float(units))
    if rc != 0:
        raise ValueError(f"native sampler rejected parameters (units="
                         f"{units})")
    return out[:n]


def install() -> bool:
    """Loads the library and installs the native samplers into noise_core.

    Returns True when the native path is active.
    """
    lib = load()
    if lib is None:
        return False
    from pipelinedp_tpu import noise_core

    def native_laplace(scale: float, size=None):
        g = noise_core.laplace_granularity(scale)
        ints = _sample(lib.pdp_sample_discrete_laplace, scale / g, size)
        noise = ints.astype(np.float64) * g
        return float(noise[0]) if size is None else noise.reshape(size)

    def native_gaussian(stddev: float, size=None):
        g = noise_core.gaussian_granularity(stddev)
        ints = _sample(lib.pdp_sample_discrete_gaussian, stddev / g, size)
        noise = ints.astype(np.float64) * g
        return float(noise[0]) if size is None else noise.reshape(size)

    noise_core.sample_laplace = native_laplace
    noise_core.sample_gaussian = native_gaussian
    return True
