// Secure discrete-noise sampling for differential privacy.
//
// Native (C++) equivalent of the security-critical sampling the reference
// delegates to Google's C++ differential-privacy library through PyDP
// (SURVEY.md section 2.4; call sites pipeline_dp/dp_computations.py:130-151).
// Naive float Laplace sampling leaks through the float representation
// (Mironov 2012); the defense here is to sample *integers* from the exact
// discrete Laplace / discrete Gaussian distributions and scale by a
// power-of-two granularity on the Python side, so the released value is a
// granularity multiple and the sampler itself never touches floating-point
// transcendentals of secret data.
//
// Sampling algorithms: Canonne, Kamath, Steinke, "The Discrete Gaussian for
// Differential Privacy" (NeurIPS 2020), Algorithms 1-3 — exact rejection
// samplers built from Bernoulli(exp(-x)) coin flips. Entropy: getrandom(2)
// (the kernel CSPRNG), buffered per thread. The only deviation from
// exactness is Bernoulli(p) on a 64-bit uniform, a bias of at most 2^-64
// per coin (the same concession Google's library makes).
//
// Deliberately NOT seedable: secure noise must not be replayable.

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <sys/random.h>

namespace {

// --- buffered kernel CSPRNG ------------------------------------------------

class EntropyBuffer {
 public:
  uint64_t NextU64() {
    if (pos_ + 8 > kBufSize) Refill();
    uint64_t out;
    std::memcpy(&out, buf_ + pos_, 8);
    pos_ += 8;
    return out;
  }

 private:
  static constexpr size_t kBufSize = 1 << 16;

  void Refill() {
    size_t got = 0;
    while (got < kBufSize) {
      ssize_t r = getrandom(buf_ + got, kBufSize - got, 0);
      if (r > 0) {
        got += static_cast<size_t>(r);
        continue;
      }
      if (r < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      // Non-retryable (ENOSYS on ancient kernels, EPERM under seccomp):
      // try /dev/urandom once, else die loudly — silently degraded entropy
      // is the one failure a secure sampler must never absorb, and this
      // runs under a ctypes call where an exception can't propagate.
      if (!RefillFromDevUrandom(got)) {
        std::fprintf(stderr,
                     "pipelinedp_tpu secure_noise: no entropy source "
                     "(getrandom errno=%d, /dev/urandom unreadable)\n",
                     errno);
        std::abort();
      }
      got = kBufSize;
    }
    pos_ = 0;
  }

  bool RefillFromDevUrandom(size_t from) {
    std::FILE* f = std::fopen("/dev/urandom", "rb");
    if (!f) return false;
    size_t need = kBufSize - from;
    size_t got = std::fread(buf_ + from, 1, need, f);
    std::fclose(f);
    return got == need;
  }

  unsigned char buf_[kBufSize];
  size_t pos_ = kBufSize;  // force refill on first use
};

thread_local EntropyBuffer tl_entropy;

// Bernoulli(p): bias <= 2^-64. The threshold p * 2^64 is computed exactly
// in 128-bit integer arithmetic from p's (mantissa, exponent) decomposition
// — no extended-precision float type involved, so the bound holds on every
// ABI (long double == double included); only the sub-2^-64 fractional part
// of the threshold is truncated, the same concession as a 64-bit uniform.
inline bool Bernoulli(double p) {
  if (p <= 0.0) return false;
  if (p >= 1.0) return true;
  int e;
  double m = std::frexp(p, &e);  // p = m * 2^e, m in [0.5, 1), e <= 0
  // 53-bit integer mantissa, exact: p = mant * 2^(e-53).
  uint64_t mant = static_cast<uint64_t>(std::ldexp(m, 53));
  int shift = e + 11;  // p * 2^64 = mant * 2^shift
  unsigned __int128 threshold;
  if (shift >= 0) {
    threshold = static_cast<unsigned __int128>(mant) << shift;
  } else if (shift > -64) {
    threshold = mant >> -shift;  // truncation bias < 2^-64
  } else {
    threshold = 0;  // p < 2^-75: below the uniform's resolution
  }
  return static_cast<unsigned __int128>(tl_entropy.NextU64()) < threshold;
}

// Unbiased Uniform{0, ..., n-1} by rejection.
inline uint64_t UniformBelow(uint64_t n) {
  uint64_t limit = UINT64_MAX - (UINT64_MAX % n);
  for (;;) {
    uint64_t u = tl_entropy.NextU64();
    if (u < limit) return u % n;
  }
}

// Bernoulli(exp(-gamma)) for gamma in [0, 1] (CKS Algorithm 1 core): count
// successes of Bernoulli(gamma/k); exp(-gamma) is the probability of an
// even count.
inline bool BernoulliExpAtMostOne(double gamma) {
  uint64_t k = 1;
  for (;;) {
    if (!Bernoulli(gamma / static_cast<double>(k))) break;
    ++k;
  }
  return (k & 1) == 1;  // k-1 successes, even
}

// Bernoulli(exp(-gamma)) for any gamma >= 0.
inline bool BernoulliExp(double gamma) {
  while (gamma > 1.0) {
    if (!BernoulliExpAtMostOne(1.0)) return false;
    gamma -= 1.0;
  }
  return BernoulliExpAtMostOne(gamma);
}

// Discrete Laplace with scale t (integer t >= 1): P(X = x) proportional to
// exp(-|x|/t). CKS Algorithm 2.
inline int64_t DiscreteLaplace(uint64_t t) {
  for (;;) {
    uint64_t u = UniformBelow(t);
    if (!BernoulliExp(static_cast<double>(u) / static_cast<double>(t)))
      continue;
    uint64_t v = 0;
    while (BernoulliExpAtMostOne(1.0)) ++v;
    uint64_t x = u + t * v;
    bool negative = Bernoulli(0.5);
    if (negative && x == 0) continue;
    int64_t xi = static_cast<int64_t>(x);
    return negative ? -xi : xi;
  }
}

// Discrete Gaussian with parameter sigma (in integer units): P(X = x)
// proportional to exp(-x^2 / (2 sigma^2)). CKS Algorithm 3: rejection from
// discrete Laplace(t), t = floor(sigma) + 1.
inline int64_t DiscreteGaussian(double sigma) {
  uint64_t t = static_cast<uint64_t>(std::floor(sigma)) + 1;
  double sigma_sq = sigma * sigma;
  for (;;) {
    int64_t y = DiscreteLaplace(t);
    double ay = static_cast<double>(y < 0 ? -y : y);
    double d = ay - sigma_sq / static_cast<double>(t);
    if (BernoulliExp(d * d / (2.0 * sigma_sq))) return y;
  }
}

template <typename T, typename Fn>
void ParallelFill(T* out, int64_t n, const Fn& sample_one) {
  const int64_t kMinPerThread = 1 << 15;
  unsigned hw = std::thread::hardware_concurrency();
  int64_t max_threads = n / kMinPerThread;
  int64_t n_threads = hw < 1 ? 1 : static_cast<int64_t>(hw);
  if (n_threads > max_threads) n_threads = max_threads;
  if (n_threads <= 1) {
    for (int64_t i = 0; i < n; ++i) out[i] = sample_one();
    return;
  }
  std::vector<std::thread> threads;
  int64_t per = (n + n_threads - 1) / n_threads;
  for (int64_t s = 0; s < n; s += per) {
    int64_t e = s + per < n ? s + per : n;
    threads.emplace_back([out, s, e, &sample_one] {
      for (int64_t i = s; i < e; ++i) out[i] = sample_one();
    });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// ABI version for the Python loader's sanity check.
int pdp_noise_abi_version() { return 2; }

// n samples of discrete Laplace with scale t_units (rounded to >= 1
// integer units). Returns 0 on success.
int pdp_sample_discrete_laplace(int64_t* out, int64_t n, double t_units) {
  if (!out || n < 0 || !(t_units > 0) || !std::isfinite(t_units)) return 1;
  uint64_t t = t_units < 1.0 ? 1 : static_cast<uint64_t>(std::llround(t_units));
  ParallelFill(out, n, [t] { return DiscreteLaplace(t); });
  return 0;
}

// n samples of discrete Gaussian with parameter sigma_units (> 0).
int pdp_sample_discrete_gaussian(int64_t* out, int64_t n,
                                 double sigma_units) {
  if (!out || n < 0 || !(sigma_units > 0) || !std::isfinite(sigma_units))
    return 1;
  ParallelFill(out, n, [sigma_units] { return DiscreteGaussian(sigma_units); });
  return 0;
}

// n uniform doubles in [0, 1) with full 53-bit precision, drawn from the
// kernel CSPRNG. Backs partition-selection keep decisions and exponential-
// mechanism draws: those comparisons ("u < keep_probability") are exactly as
// release-critical as additive noise, so they must not ride a seedable
// userspace PRNG.
int pdp_sample_uniform_double(double* out, int64_t n) {
  if (!out || n < 0) return 1;
  ParallelFill(out, n, [] {
    return static_cast<double>(tl_entropy.NextU64() >> 11) * 0x1.0p-53;
  });
  return 0;
}

}  // extern "C"
