// Native row bucketing + byte-packing for the streaming execution path.
//
// The streaming engine (pipelinedp_tpu/ops/streaming.py) hash-shards rows
// by privacy id into pid-disjoint buckets and ships each bucket byte-packed
// to the device. Doing that with numpy costs one full-array pass per bucket
// (flatnonzero + three gathers + byte splits, ~1 s per bucket at the
// benchmark scale); this helper does the whole job in one two-pass radix
// partition over the input, multithreaded, writing the packed per-bucket
// buffers directly. Role: the native data-loader stage (SURVEY.md §2.5 —
// the reference delegates its loader hot path to Beam/Spark native runners).
//
// Layout written: out[bucket][slot] = bytes_pid little-endian bytes of
// (pid - pid_lo) | bytes_pk bytes of pk | 4 bytes f32 value (or 2 bytes
// f16 when value_f16). Buckets are pid-disjoint by construction
// (bucket = knuth_hash(pid - pid_lo) % n_buckets, identical to the Python
// fallback in streaming.py).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cmath>
#include <functional>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kHashMult = 2654435761u;

// Worker-pool width override (0 = auto: hardware_concurrency capped at
// 16). Set through pdp_set_encode_threads — the Python loader wires the
// validated PIPELINEDP_TPU_ENCODE_THREADS value through before encode
// calls. Output is bit-identical for every width: workers own disjoint
// buckets (RunPool) or disjoint input ranges with precomputed write
// offsets (pdp_pack_buckets), so the thread count only changes wall
// time, never bytes.
std::atomic<int> g_encode_threads{0};

int64_t PoolWidth(int64_t auto_cap) {
  const int forced = g_encode_threads.load(std::memory_order_relaxed);
  if (forced > 0) return forced;
  unsigned hw = std::thread::hardware_concurrency();
  int64_t n = hw < 1 ? 1 : static_cast<int64_t>(hw);
  return n > auto_cap ? auto_cap : n;
}

inline uint32_t BucketOf(int32_t shifted, uint32_t n_buckets) {
  return ((static_cast<uint32_t>(shifted) * kHashMult) >> 16) % n_buckets;
}

// f32 -> f16 (round-to-nearest-even), bit-level.
inline uint16_t F32ToF16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((x >> 23) & 0xff) - 127 + 15;
  uint32_t mant = x & 0x7fffffu;
  if (exp >= 31) {
    // Overflow -> inf; NaN keeps a nonzero mantissa (matching numpy's
    // f32->f16 cast so the packer and the fallback stay bit-identical).
    if (((x >> 23) & 0xff) == 255 && mant) {
      uint32_t m = mant >> 13;
      if (!m) m = 1;
      return static_cast<uint16_t>(sign | 0x7c00u | m);
    }
    return static_cast<uint16_t>(sign | 0x7c00u);
  }
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half & 1u))) half += 1;
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) half += 1;
  return static_cast<uint16_t>(sign | half);
}

struct PackArgs {
  const int32_t* pid;
  const int32_t* pk;
  const float* value;
  int64_t n;
  int32_t pid_lo;
  uint32_t n_buckets;
  int bytes_pid;
  int bytes_pk;
  bool value_f16;
  uint8_t* out;
  int64_t cap;
  int width;
};

inline void WriteRow(const PackArgs& a, int64_t row, uint8_t* dst) {
  uint32_t spid = static_cast<uint32_t>(a.pid[row] - a.pid_lo);
  for (int b = 0; b < a.bytes_pid; ++b) dst[b] = (spid >> (8 * b)) & 0xff;
  uint32_t pk = static_cast<uint32_t>(a.pk[row]);
  uint8_t* d = dst + a.bytes_pid;
  for (int b = 0; b < a.bytes_pk; ++b) d[b] = (pk >> (8 * b)) & 0xff;
  d += a.bytes_pk;
  if (a.value_f16) {
    uint16_t h = F32ToF16(a.value ? a.value[row] : 0.0f);
    d[0] = h & 0xff;
    d[1] = (h >> 8) & 0xff;
  } else {
    float v = a.value ? a.value[row] : 0.0f;
    std::memcpy(d, &v, 4);
  }
}

}  // namespace

extern "C" {

// Two-pass multithreaded radix partition + byte pack.
//   out: n_buckets * cap * width bytes (bucket-major).
//   counts: n_buckets entries, filled with rows per bucket.
// Returns 0 on success, 1 on bad args, 2 if any bucket exceeds cap
// (counts still valid — caller re-allocates with counts.max() and retries).
int pdp_pack_buckets(const int32_t* pid, const int32_t* pk,
                     const float* value, int64_t n, int32_t pid_lo,
                     int64_t n_buckets, int bytes_pid, int bytes_pk,
                     int value_f16, uint8_t* out, int64_t cap,
                     int64_t* counts) {
  if (!pid || !pk || !out || !counts || n < 0 || n_buckets <= 0 ||
      bytes_pid < 1 || bytes_pid > 4 || bytes_pk < 1 || bytes_pk > 4) {
    return 1;
  }
  PackArgs args{pid,      pk,       value,
                n,        pid_lo,   static_cast<uint32_t>(n_buckets),
                bytes_pid, bytes_pk, value_f16 != 0,
                out,      cap,      bytes_pid + bytes_pk + (value_f16 ? 2 : 4)};

  int64_t n_threads = PoolWidth(16);
  if (g_encode_threads.load(std::memory_order_relaxed) <= 0 &&
      n < (1 << 16)) {
    n_threads = 1;  // auto mode: thread spawn beats the work below 64k rows
  }
  if (n_threads > n && n > 0) n_threads = n;
  if (n_threads < 1) n_threads = 1;
  int64_t per = (n + n_threads - 1) / n_threads;

  // Pass 1: per-thread per-bucket counts.
  std::vector<std::vector<int64_t>> thread_counts(
      n_threads, std::vector<int64_t>(n_buckets, 0));
  {
    std::vector<std::thread> threads;
    for (int64_t t = 0; t < n_threads; ++t) {
      threads.emplace_back([&, t] {
        int64_t lo = t * per;
        int64_t hi = lo + per < n ? lo + per : n;
        auto& local = thread_counts[t];
        for (int64_t i = lo; i < hi; ++i) {
          local[BucketOf(pid[i] - pid_lo, args.n_buckets)] += 1;
        }
      });
    }
    for (auto& th : threads) th.join();
  }
  // Per-(thread, bucket) write offsets; totals into counts.
  std::vector<std::vector<int64_t>> offsets(
      n_threads, std::vector<int64_t>(n_buckets, 0));
  bool overflow = false;
  for (int64_t b = 0; b < n_buckets; ++b) {
    int64_t acc = 0;
    for (int64_t t = 0; t < n_threads; ++t) {
      offsets[t][b] = acc;
      acc += thread_counts[t][b];
    }
    counts[b] = acc;
    if (acc > cap) overflow = true;
  }
  if (overflow) return 2;

  // Pass 2: write rows, bucket-major output, per-thread disjoint slots.
  {
    std::vector<std::thread> threads;
    for (int64_t t = 0; t < n_threads; ++t) {
      threads.emplace_back([&, t] {
        int64_t lo = t * per;
        int64_t hi = lo + per < n ? lo + per : n;
        auto local = offsets[t];  // copy: mutated as we write
        for (int64_t i = lo; i < hi; ++i) {
          uint32_t b = BucketOf(pid[i] - pid_lo, args.n_buckets);
          int64_t slot = local[b]++;
          uint8_t* dst =
              out + (static_cast<int64_t>(b) * cap + slot) * args.width;
          WriteRow(args, i, dst);
        }
      });
    }
    for (auto& th : threads) th.join();
  }
  return 0;
}

}  // extern "C"


// ---------------------------------------------------------------------------
// Lossless RLE + bit-plane wire codec (native fast path; the numpy
// reference in ops/wirecodec.py produces bit-identical buffers).
//
// Three-call API so the per-slab encode can overlap the previous slab's
// async host->device transfer (ops/streaming.py drives it):
//   pdp_rle_prep        one pass: bucket rows (pid-hash, same bucketing as
//                       pdp_pack_buckets) into bucket-major SoA temps, and
//                       (span permitting) exact per-bucket RLE entry
//                       counts — so the wire format is known BEFORE any
//                       sorting and the sort can pipeline per slab.
//   pdp_rle_sort_range  per bucket: LSD radix sort by shifted pid (stable,
//                       byte passes only up to the bucket's max id) +
//                       exact RLE entry counts. The expensive step —
//                       callers interleave it slab-by-slab with emit +
//                       device_put so it hides behind transfer + kernel.
//   pdp_rle_emit_range  per bucket: write one flat slab row =
//                       [uniq ids | uint16 run lengths | pk bit-planes |
//                       value planes/raw], runs split at 65535 — or, in
//                       pid_mode 1, unsorted pid bit-planes (no host sort;
//                       the device kernel sorts anyway).
//   pdp_rle_free        release the state.
//
// Bit-planes are LSB-first: plane j, byte r>>3, bit r&7 = bit j of row r.
// Packing works in 8-row register groups (one byte store per plane per 8
// rows) — this box may have a single core, so the encoder is tuned for
// single-thread throughput first, with an optional bucket-parallel pool.
// ---------------------------------------------------------------------------

namespace {

constexpr int64_t kRunSplit = 65535;

// Largest (pid_span + 1) for which prep builds the per-pid count table
// that yields exact RLE entry counts BEFORE any sorting (the count table
// is 4 bytes per id in the span). Knowing the entry counts up front lets
// the caller fix the wire format immediately and pipeline the per-bucket
// radix sort behind the transfers instead of running it all up front
// (ops/streaming.py drives this).
constexpr int64_t kMaxEntryCountSpan = int64_t{1} << 26;

struct RleState {
  int64_t n = 0;
  int64_t k = 0;
  int value_mode = 0;  // 0 none, 1 planes(vidx), 2 raw f32, 3 raw f16
  bool low_grouped = false;  // prep pre-grouped rows by pid low byte
  std::vector<int64_t> bucket_start;  // [k+1]
  // Bucket-major SoA; after sort_range a bucket's slice is pid-sorted.
  std::vector<uint32_t> tpid;
  std::vector<int32_t> tpk;
  std::vector<float> tval;
  std::vector<int32_t> tvidx;
  std::vector<char> sorted;  // per bucket
};

// Stable LSD radix sort of (pid << 32 | local_index) pairs, key byte
// passes [first_pass, nbytes). Stability (index in the low bits) makes
// the row order identical to numpy's kind="stable" argsort in the
// reference encoder; prep's scatter already performed pass 0 (grouping by
// the pid low byte), so sorts normally start at pass 1.
void RadixSortPairs(uint64_t* a, uint64_t* tmp, int64_t m, int first_pass,
                    int nbytes) {
  for (int p = first_pass; p < nbytes; ++p) {
    const int shift = 32 + 8 * p;
    int64_t hist[256] = {0};
    for (int64_t i = 0; i < m; ++i) hist[(a[i] >> shift) & 0xff]++;
    int64_t acc = 0;
    for (int v = 0; v < 256; ++v) {
      int64_t c = hist[v];
      hist[v] = acc;
      acc += c;
    }
    for (int64_t i = 0; i < m; ++i) tmp[hist[(a[i] >> shift) & 0xff]++] = a[i];
    std::swap(a, tmp);
  }
  if ((nbytes - first_pass) & 1) {
    std::memcpy(tmp, a, m * 8);  // result back into caller's a
  }
}

void SortBucket(RleState* st, int64_t b) {
  const int64_t s = st->bucket_start[b];
  const int64_t m = st->bucket_start[b + 1] - s;
  if (m == 0 || st->sorted[b]) {
    st->sorted[b] = 1;
    return;
  }
  uint32_t maxpid = 0;
  for (int64_t i = 0; i < m; ++i) maxpid |= st->tpid[s + i];
  int nbytes = 1;
  while (nbytes < 4 && (maxpid >> (8 * nbytes))) ++nbytes;
  const int first_pass = st->low_grouped ? 1 : 0;
  if (nbytes <= first_pass) {
    st->sorted[b] = 1;  // single-byte ids: the prep grouping IS the sort
    return;
  }
  std::vector<uint64_t> a(m), tmp(m);
  for (int64_t i = 0; i < m; ++i) {
    a[i] = (static_cast<uint64_t>(st->tpid[s + i]) << 32) |
           static_cast<uint64_t>(i);
  }
  // RadixSortPairs leaves the sorted pairs in `a` for any pass count (odd
  // counts copy back).
  RadixSortPairs(a.data(), tmp.data(), m, first_pass, nbytes);
  const uint64_t* order = a.data();
  // Permute payload columns into sorted order via one gather each.
  {
    std::vector<int32_t> scratch(m);
    for (int64_t i = 0; i < m; ++i) {
      scratch[i] = st->tpk[s + (order[i] & 0xffffffffu)];
    }
    std::memcpy(&st->tpk[s], scratch.data(), m * 4);
    if (st->value_mode == 1) {
      for (int64_t i = 0; i < m; ++i) {
        scratch[i] = st->tvidx[s + (order[i] & 0xffffffffu)];
      }
      std::memcpy(&st->tvidx[s], scratch.data(), m * 4);
    } else if (st->value_mode == 2 || st->value_mode == 3) {
      float* fs = reinterpret_cast<float*>(scratch.data());
      for (int64_t i = 0; i < m; ++i) {
        fs[i] = st->tval[s + (order[i] & 0xffffffffu)];
      }
      std::memcpy(&st->tval[s], scratch.data(), m * 4);
    }
  }
  for (int64_t i = 0; i < m; ++i) {
    st->tpid[s + i] = static_cast<uint32_t>(order[i] >> 32);
  }
  st->sorted[b] = 1;
}

int64_t CountRleEntries(const RleState* st, int64_t b) {
  const int64_t s = st->bucket_start[b];
  const int64_t m = st->bucket_start[b + 1] - s;
  int64_t entries = 0, run = 0;
  uint32_t prev = 0;
  for (int64_t i = 0; i < m; ++i) {
    const uint32_t id = st->tpid[s + i];
    if (i == 0 || id != prev || run == kRunSplit) {
      if (i != 0) ++entries;
      prev = id;
      run = 0;
    }
    ++run;
  }
  if (m > 0) ++entries;
  return entries;
}

// Bit-plane pack `col[0..m)` (values < 2^bits) into planes at out
// (stride cap8 bytes per plane), 8 rows per byte store.
void PackPlanes(const int32_t* col, int64_t m, int bits, int64_t cap8,
                uint8_t* out) {
  for (int64_t r8 = 0; r8 * 8 < m; ++r8) {
    const int g = static_cast<int>(m - r8 * 8 < 8 ? m - r8 * 8 : 8);
    uint32_t vals[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int i = 0; i < g; ++i) {
      vals[i] = static_cast<uint32_t>(col[r8 * 8 + i]);
    }
    for (int j = 0; j < bits; ++j) {
      uint8_t byte = 0;
      for (int i = 0; i < 8; ++i) byte |= ((vals[i] >> j) & 1u) << i;
      out[j * cap8 + r8] = byte;
    }
  }
}

void RunPool(int64_t k0, int64_t k1, const std::function<void(int64_t)>& fn) {
  int64_t pool = PoolWidth(16);
  if (pool > k1 - k0) pool = k1 - k0;
  if (pool <= 1) {
    for (int64_t b = k0; b < k1; ++b) fn(b);
    return;
  }
  std::atomic<int64_t> next{k0};
  std::vector<std::thread> threads;
  for (int64_t t = 0; t < pool; ++t) {
    threads.emplace_back([&] {
      for (;;) {
        const int64_t b = next.fetch_add(1);
        if (b >= k1) return;
        fn(b);
      }
    });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Prep: one counting pass + one scatter pass into bucket-major SoA temps.
// The scatter ALSO groups rows by the pid low byte inside each bucket —
// that is pass 0 of the stable LSD radix sort, so sort_range only runs
// the remaining byte passes.
//
// value_mode 1 with vidx == NULL computes the affine value index inline:
// idx = rint((value - v_lo) / v_scale), verified bit-exact against the
// float32 reconstruction the device performs. stats[0] is set to 1 (and
// nullptr returned) if any row fails verification or leaves [0, 2^20);
// stats[1] returns the maximum index (for the bit-width of the planes);
// stats[2] returns the max rows of any single pid when the count table
// was built (ABI 7; -1 otherwise) — it bounds every pid segment in every
// bucket, sizing the tile slack of the kernel's segment-local sort.
//
// pid_span / n_entries: when n_entries is non-null and the shifted pid
// span fits the count-table budget, n_entries[b] receives the EXACT
// post-sort RLE entry count of bucket b (sum of ceil(rows_per_pid /
// 65535) over the bucket's pids — a pid maps to exactly one bucket, so
// this equals what pdp_rle_sort_range will report), computed without
// sorting. Otherwise n_entries[0] is set to -1 and the caller falls back
// to learning entry counts from the upfront sort.
void* pdp_rle_prep(const int32_t* pid, const int32_t* pk, const float* value,
                   const int32_t* vidx, double v_lo, double v_scale,
                   int64_t n, int32_t pid_lo, int64_t k, int value_mode,
                   int64_t pid_span, int64_t* n_entries,
                   int64_t* n_rows, int64_t* stats) {
  if (!pid || !pk || !n_rows || !stats || n < 0 || k <= 0) return nullptr;
  const bool inline_vidx = value_mode == 1 && vidx == nullptr;
  if (value_mode == 1 && !vidx && !value) return nullptr;
  if ((value_mode == 2 || value_mode == 3 || inline_vidx) && !value) {
    return nullptr;
  }
  stats[0] = 0;
  stats[1] = 0;
  stats[2] = -1;
  auto* st = new RleState();
  st->n = n;
  st->k = k;
  st->value_mode = value_mode;
  st->low_grouped = true;
  st->bucket_start.assign(k + 1, 0);
  st->sorted.assign(k, 0);
  // Pass 1: counts per (bucket, pid low byte) — the sub-cursor table that
  // makes the scatter double as radix pass 0 — plus (when the span fits
  // the budget) a per-pid count table for the exact RLE entry counts.
  const bool count_entries =
      n_entries != nullptr && pid_span >= 0 &&
      pid_span + 1 <= kMaxEntryCountSpan &&
      n <= static_cast<int64_t>(UINT32_MAX) / 2;
  std::vector<uint32_t> pid_count;
  if (count_entries) pid_count.assign(pid_span + 1, 0);
  std::vector<int64_t> sub(k * 256, 0);
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t spid = static_cast<uint32_t>(pid[i] - pid_lo);
    sub[(static_cast<int64_t>(BucketOf(pid[i] - pid_lo,
                                       static_cast<uint32_t>(k)))
         << 8) | (spid & 0xff)]++;
    if (count_entries) pid_count[spid]++;
  }
  if (n_entries != nullptr) {
    if (count_entries) {
      for (int64_t b = 0; b < k; ++b) n_entries[b] = 0;
      int64_t max_run = 0;
      for (int64_t s = 0; s <= pid_span; ++s) {
        const uint32_t c = pid_count[s];
        if (c) {
          if (static_cast<int64_t>(c) > max_run) {
            max_run = static_cast<int64_t>(c);
          }
          n_entries[BucketOf(static_cast<int32_t>(s),
                             static_cast<uint32_t>(k))] +=
              (c + kRunSplit - 1) / kRunSplit;
        }
      }
      stats[2] = max_run;
    } else {
      n_entries[0] = -1;
    }
  }
  {
    int64_t acc = 0;
    for (int64_t b = 0; b < k; ++b) {
      st->bucket_start[b] = acc;
      int64_t bucket_total = 0;
      for (int v = 0; v < 256; ++v) {
        const int64_t c = sub[(b << 8) | v];
        sub[(b << 8) | v] = acc + bucket_total;
        bucket_total += c;
      }
      n_rows[b] = bucket_total;
      acc += bucket_total;
    }
    st->bucket_start[k] = acc;
  }
  st->tpid.resize(n);
  st->tpk.resize(n);
  if (value_mode == 2 || value_mode == 3) st->tval.resize(n);
  if (value_mode == 1) st->tvidx.resize(n);
  const float lo_f = static_cast<float>(v_lo);
  const float scale_f = static_cast<float>(v_scale);
  bool verify_failed = false;
  int64_t max_idx = 0;
  for (int64_t i = 0; i < n; ++i) {
    const uint32_t spid = static_cast<uint32_t>(pid[i] - pid_lo);
    const uint32_t b = BucketOf(pid[i] - pid_lo, static_cast<uint32_t>(k));
    const int64_t slot = sub[(static_cast<int64_t>(b) << 8) |
                             (spid & 0xff)]++;
    st->tpid[slot] = spid;
    st->tpk[slot] = pk[i];
    if (value_mode == 2 || value_mode == 3) st->tval[slot] = value[i];
    if (value_mode == 1) {
      if (inline_vidx) {
        if (verify_failed) break;  // state is discarded on failure
        const float v = value[i];
        // nearbyint: ties-to-even, matching the numpy reference's np.rint
        // so native and fallback encoders emit bit-identical buffers.
        const int64_t idx = static_cast<int64_t>(
            std::nearbyint((static_cast<double>(v) - v_lo) / v_scale));
        if (idx < 0 || idx >= (1 << 20)) {
          verify_failed = true;
          st->tvidx[slot] = 0;
          continue;
        }
        const float rec = lo_f + static_cast<float>(idx) * scale_f;
        uint32_t rb, vb;
        std::memcpy(&rb, &rec, 4);
        std::memcpy(&vb, &v, 4);
        if (rb != vb) verify_failed = true;
        if (idx > max_idx) max_idx = idx;
        st->tvidx[slot] = static_cast<int32_t>(idx);
      } else {
        st->tvidx[slot] = vidx[i];
      }
    }
  }
  if (inline_vidx && verify_failed) {
    stats[0] = 1;
    delete st;
    return nullptr;
  }
  stats[1] = max_idx;
  return st;
}

int pdp_rle_sort_range(void* handle, int64_t b0, int64_t b1,
                       int64_t* n_uniq) {
  auto* st = static_cast<RleState*>(handle);
  if (!st || !n_uniq || b0 < 0 || b1 > st->k || b0 > b1) return 1;
  RunPool(b0, b1, [&](int64_t b) {
    SortBucket(st, b);
    n_uniq[b - b0] = CountRleEntries(st, b);
  });
  return 0;
}

// out: [b1-b0, width] flat slab rows.
// pid_mode 0 (RLE): buckets must be sorted; width = ucap*bytes_pid +
//   ucap*2 + bits_pk*cap/8 + value bytes.
// pid_mode 1 (bit-planes): pids ship as bits_pid planes in arrival order —
//   NO host sort required (the device kernel sorts anyway); width =
//   bits_pid*cap/8 + bits_pk*cap/8 + value bytes, and ucap is ignored.
int pdp_rle_emit_range(void* handle, int64_t b0, int64_t b1, int pid_mode,
                       int bytes_pid, int bits_pid,
                       int bits_pk, int bits_val, int64_t cap, int64_t ucap,
                       uint8_t* out, int64_t width) {
  auto* st = static_cast<RleState*>(handle);
  const bool planes = pid_mode == 1;
  if (!st || !out || b0 < 0 || b1 > st->k || b0 > b1 || cap < 8 ||
      (cap % 8) != 0 || bits_pk < 1 || bits_pk > 31 ||
      (planes ? (bits_pid < 1 || bits_pid > 31)
              : (bytes_pid < 1 || bytes_pid > 4 || ucap < 1))) {
    return 1;
  }
  if (st->value_mode == 1 && (bits_val < 1 || bits_val > 31)) return 1;
  const int64_t cap8 = cap / 8;
  const int64_t o_cnt = planes ? bits_pid * cap8 : ucap * bytes_pid;
  const int64_t o_pk = planes ? o_cnt : o_cnt + ucap * 2;
  const int64_t o_val = o_pk + bits_pk * cap8;
  int64_t want = o_val;
  if (st->value_mode == 1) want += bits_val * cap8;
  if (st->value_mode == 2) want += cap * 4;
  if (st->value_mode == 3) want += cap * 2;
  if (want != width) return 1;

  std::atomic<int> rc{0};
  RunPool(b0, b1, [&](int64_t b) {
    const int64_t s = st->bucket_start[b];
    const int64_t m = st->bucket_start[b + 1] - s;
    if ((!planes && !st->sorted[b]) || m > cap) {
      rc.store(2);
      return;
    }
    uint8_t* row = out + (b - b0) * width;
    std::memset(row, 0, width);
    if (planes) {
      // Arrival-order pid planes (shifted ids < 2^bits_pid).
      PackPlanes(reinterpret_cast<const int32_t*>(&st->tpid[s]), m,
                 bits_pid, cap8, row);
    } else {
      // RLE of the sorted pid column.
      int64_t entries = 0, run = 0;
      uint32_t prev = 0;
      auto flush = [&](uint32_t id, int64_t len) {
        if (entries >= ucap) {
          rc.store(3);
          return false;
        }
        uint8_t* u = row + entries * bytes_pid;
        for (int bb = 0; bb < bytes_pid; ++bb) {
          u[bb] = (id >> (8 * bb)) & 0xff;
        }
        row[o_cnt + entries * 2] = len & 0xff;
        row[o_cnt + entries * 2 + 1] = (len >> 8) & 0xff;
        ++entries;
        return true;
      };
      for (int64_t i = 0; i < m; ++i) {
        const uint32_t id = st->tpid[s + i];
        if (i == 0) {
          prev = id;
          run = 0;
        } else if (id != prev || run == kRunSplit) {
          if (!flush(prev, run)) return;
          prev = id;
          run = 0;
        }
        ++run;
      }
      if (m > 0 && !flush(prev, run)) return;
    }
    // pk planes, then the value column.
    PackPlanes(&st->tpk[s], m, bits_pk, cap8, row + o_pk);
    if (st->value_mode == 1) {
      PackPlanes(&st->tvidx[s], m, bits_val, cap8, row + o_val);
    } else if (st->value_mode == 2) {
      std::memcpy(row + o_val, &st->tval[s], m * 4);
    } else if (st->value_mode == 3) {
      uint8_t* v = row + o_val;
      for (int64_t i = 0; i < m; ++i) {
        const uint16_t h = F32ToF16(st->tval[s + i]);
        v[i * 2] = h & 0xff;
        v[i * 2 + 1] = (h >> 8) & 0xff;
      }
    }
  });
  return rc.load();
}

void pdp_rle_free(void* handle) { delete static_cast<RleState*>(handle); }

// Encode worker-pool width: 0 restores auto (hardware_concurrency capped
// at 16); values are clamped to [0, 64]. Applies to pdp_pack_buckets,
// pdp_rle_sort_range and pdp_rle_emit_range. The callers' loader wires
// PIPELINEDP_TPU_ENCODE_THREADS through here.
void pdp_set_encode_threads(int n) {
  if (n < 0) n = 0;
  if (n > 64) n = 64;
  g_encode_threads.store(n, std::memory_order_relaxed);
}

int pdp_get_encode_threads() {
  return g_encode_threads.load(std::memory_order_relaxed);
}

int pdp_row_packer_abi_version() { return 7; }

}  // extern "C"
