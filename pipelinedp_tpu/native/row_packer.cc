// Native row bucketing + byte-packing for the streaming execution path.
//
// The streaming engine (pipelinedp_tpu/ops/streaming.py) hash-shards rows
// by privacy id into pid-disjoint buckets and ships each bucket byte-packed
// to the device. Doing that with numpy costs one full-array pass per bucket
// (flatnonzero + three gathers + byte splits, ~1 s per bucket at the
// benchmark scale); this helper does the whole job in one two-pass radix
// partition over the input, multithreaded, writing the packed per-bucket
// buffers directly. Role: the native data-loader stage (SURVEY.md §2.5 —
// the reference delegates its loader hot path to Beam/Spark native runners).
//
// Layout written: out[bucket][slot] = bytes_pid little-endian bytes of
// (pid - pid_lo) | bytes_pk bytes of pk | 4 bytes f32 value (or 2 bytes
// f16 when value_f16). Buckets are pid-disjoint by construction
// (bucket = knuth_hash(pid - pid_lo) % n_buckets, identical to the Python
// fallback in streaming.py).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kHashMult = 2654435761u;

inline uint32_t BucketOf(int32_t shifted, uint32_t n_buckets) {
  return ((static_cast<uint32_t>(shifted) * kHashMult) >> 16) % n_buckets;
}

// f32 -> f16 (round-to-nearest-even), bit-level.
inline uint16_t F32ToF16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((x >> 23) & 0xff) - 127 + 15;
  uint32_t mant = x & 0x7fffffu;
  if (exp >= 31) {
    // Overflow -> inf; NaN keeps a nonzero mantissa (matching numpy's
    // f32->f16 cast so the packer and the fallback stay bit-identical).
    if (((x >> 23) & 0xff) == 255 && mant) {
      uint32_t m = mant >> 13;
      if (!m) m = 1;
      return static_cast<uint16_t>(sign | 0x7c00u | m);
    }
    return static_cast<uint16_t>(sign | 0x7c00u);
  }
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half & 1u))) half += 1;
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) half += 1;
  return static_cast<uint16_t>(sign | half);
}

struct PackArgs {
  const int32_t* pid;
  const int32_t* pk;
  const float* value;
  int64_t n;
  int32_t pid_lo;
  uint32_t n_buckets;
  int bytes_pid;
  int bytes_pk;
  bool value_f16;
  uint8_t* out;
  int64_t cap;
  int width;
};

inline void WriteRow(const PackArgs& a, int64_t row, uint8_t* dst) {
  uint32_t spid = static_cast<uint32_t>(a.pid[row] - a.pid_lo);
  for (int b = 0; b < a.bytes_pid; ++b) dst[b] = (spid >> (8 * b)) & 0xff;
  uint32_t pk = static_cast<uint32_t>(a.pk[row]);
  uint8_t* d = dst + a.bytes_pid;
  for (int b = 0; b < a.bytes_pk; ++b) d[b] = (pk >> (8 * b)) & 0xff;
  d += a.bytes_pk;
  if (a.value_f16) {
    uint16_t h = F32ToF16(a.value ? a.value[row] : 0.0f);
    d[0] = h & 0xff;
    d[1] = (h >> 8) & 0xff;
  } else {
    float v = a.value ? a.value[row] : 0.0f;
    std::memcpy(d, &v, 4);
  }
}

}  // namespace

extern "C" {

// Two-pass multithreaded radix partition + byte pack.
//   out: n_buckets * cap * width bytes (bucket-major).
//   counts: n_buckets entries, filled with rows per bucket.
// Returns 0 on success, 1 on bad args, 2 if any bucket exceeds cap
// (counts still valid — caller re-allocates with counts.max() and retries).
int pdp_pack_buckets(const int32_t* pid, const int32_t* pk,
                     const float* value, int64_t n, int32_t pid_lo,
                     int64_t n_buckets, int bytes_pid, int bytes_pk,
                     int value_f16, uint8_t* out, int64_t cap,
                     int64_t* counts) {
  if (!pid || !pk || !out || !counts || n < 0 || n_buckets <= 0 ||
      bytes_pid < 1 || bytes_pid > 4 || bytes_pk < 1 || bytes_pk > 4) {
    return 1;
  }
  PackArgs args{pid,      pk,       value,
                n,        pid_lo,   static_cast<uint32_t>(n_buckets),
                bytes_pid, bytes_pk, value_f16 != 0,
                out,      cap,      bytes_pid + bytes_pk + (value_f16 ? 2 : 4)};

  unsigned hw = std::thread::hardware_concurrency();
  int64_t n_threads = hw < 1 ? 1 : static_cast<int64_t>(hw);
  if (n_threads > 16) n_threads = 16;
  if (n < (1 << 16)) n_threads = 1;
  int64_t per = (n + n_threads - 1) / n_threads;

  // Pass 1: per-thread per-bucket counts.
  std::vector<std::vector<int64_t>> thread_counts(
      n_threads, std::vector<int64_t>(n_buckets, 0));
  {
    std::vector<std::thread> threads;
    for (int64_t t = 0; t < n_threads; ++t) {
      threads.emplace_back([&, t] {
        int64_t lo = t * per;
        int64_t hi = lo + per < n ? lo + per : n;
        auto& local = thread_counts[t];
        for (int64_t i = lo; i < hi; ++i) {
          local[BucketOf(pid[i] - pid_lo, args.n_buckets)] += 1;
        }
      });
    }
    for (auto& th : threads) th.join();
  }
  // Per-(thread, bucket) write offsets; totals into counts.
  std::vector<std::vector<int64_t>> offsets(
      n_threads, std::vector<int64_t>(n_buckets, 0));
  bool overflow = false;
  for (int64_t b = 0; b < n_buckets; ++b) {
    int64_t acc = 0;
    for (int64_t t = 0; t < n_threads; ++t) {
      offsets[t][b] = acc;
      acc += thread_counts[t][b];
    }
    counts[b] = acc;
    if (acc > cap) overflow = true;
  }
  if (overflow) return 2;

  // Pass 2: write rows, bucket-major output, per-thread disjoint slots.
  {
    std::vector<std::thread> threads;
    for (int64_t t = 0; t < n_threads; ++t) {
      threads.emplace_back([&, t] {
        int64_t lo = t * per;
        int64_t hi = lo + per < n ? lo + per : n;
        auto local = offsets[t];  // copy: mutated as we write
        for (int64_t i = lo; i < hi; ++i) {
          uint32_t b = BucketOf(pid[i] - pid_lo, args.n_buckets);
          int64_t slot = local[b]++;
          uint8_t* dst =
              out + (static_cast<int64_t>(b) * cap + slot) * args.width;
          WriteRow(args, i, dst);
        }
      });
    }
    for (auto& th : threads) th.join();
  }
  return 0;
}

int pdp_row_packer_abi_version() { return 1; }

}  // extern "C"
