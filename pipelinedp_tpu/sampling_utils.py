"""Sampling helpers used by contribution bounding.

Parity: pipeline_dp/sampling_utils.py (choose_from_list_without_replacement
:19, ValueSampler :38-51). The JAX backend has its own batched counterparts
in pipelinedp_tpu/ops/sampling.py; these host-side versions serve the
LocalBackend correctness oracle.
"""

from __future__ import annotations

import hashlib
from typing import Any, List

import numpy as np

from pipelinedp_tpu import noise_core


def choose_from_list_without_replacement(a: List[Any], size: int) -> List[Any]:
    """Uniformly samples ``size`` elements without replacement.

    Returns the input list unchanged when it is already small enough. Sampling
    is done over indices so elements keep their native Python types (no numpy
    casting — matters for both serialization and arbitrary-precision ints).

    Which contributions survive bounding decides whose data reaches the
    mechanism, so the draw comes from noise_core's secure uniform sampler
    (kernel CSPRNG when the native library is available; the seedable
    fallback only after noise_core.seed_fallback_rng) rather than the
    predictable global numpy state: each index gets a uniform draw and the
    ``size`` smallest are kept — distributionally identical to
    np.random.choice(replace=False).
    """
    if len(a) <= size:
        return a
    uniforms = np.asarray(noise_core.sample_uniform(len(a)))
    picked = np.argpartition(uniforms, size)[:size]
    return [a[i] for i in picked]


def _hash64(value: Any) -> int:
    digest = hashlib.sha1(repr(value).encode()).hexdigest()
    return int(digest[:16], 16)


class ValueSampler:
    """Deterministic hash-based Bernoulli sampler.

    ``keep(v)`` is a fixed function of ``v``; over uniformly random values the
    keep probability equals ``sampling_rate``. Used for deterministic
    partition subsampling in the analysis layer.
    """

    def __init__(self, sampling_rate: float):
        if not 0 < sampling_rate <= 1:
            raise ValueError(
                f"sampling_rate must be in (0, 1], got {sampling_rate}")
        self._keep_bound = int(round(2**64 * sampling_rate))

    def keep(self, value: Any) -> bool:
        return _hash64(value) < self._keep_bound
