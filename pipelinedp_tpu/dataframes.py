"""DP QueryBuilder over columnar frames (pandas or dict-of-arrays).

High-level SQL-ish API: ``QueryBuilder(df, "user_id").groupby(...).count()
.sum(...).mean(...).build_query().run_query(Budget(...))``. Role parity with
the reference's Spark-DataFrame query builder
(/root/reference/pipeline_dp/dataframes.py:264-495), redesigned for the
columnar TPU engine: the input is a pandas DataFrame or a plain
``{column: np.ndarray}`` dict, the columns feed ``JaxDPEngine`` as
``ColumnarData`` with no per-row conversion, and the DP result comes back
as a frame of the same kind.

Extras over the reference builder: ``variance``, ``privacy_id_count`` and
``percentile`` aggregations (the engine supports them, so the builder
exposes them), and an ``engine=`` knob on ``run_query`` to run the same
query on the host oracle (``DPEngine`` + ``LocalBackend``) instead of the
TPU path.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import input_validators
from pipelinedp_tpu.aggregate_params import Metric, Metrics, NoiseKind
from pipelinedp_tpu.data_extractors import DataExtractors
from pipelinedp_tpu.ops.encoding import ColumnarData


@dataclasses.dataclass
class Budget:
    """Total (epsilon, delta) for one query."""
    epsilon: float
    delta: float = 0

    def __post_init__(self):
        input_validators.validate_epsilon_delta(self.epsilon, self.delta,
                                                "Budget")


@dataclasses.dataclass
class Columns:
    privacy_key: str
    partition_key: Union[str, Sequence[str]]
    value: Optional[str]


@dataclasses.dataclass
class ContributionBounds:
    max_partitions_contributed: Optional[int] = None
    max_contributions_per_partition: Optional[int] = None
    min_value: Optional[float] = None
    max_value: Optional[float] = None


class FrameConverter(abc.ABC):
    """Conversion between a user frame type and engine columns."""

    @abc.abstractmethod
    def frame_to_columns(self, df, columns: Columns) -> ColumnarData:
        """Extracts (pid, pk, value) columns from the frame."""

    @abc.abstractmethod
    def columns_to_frame(self, data: Dict[str, np.ndarray]):
        """Builds an output frame from named result columns."""

    @abc.abstractmethod
    def column_names(self, df) -> List[str]:
        """Column names present in the frame."""


class PandasConverter(FrameConverter):
    """pandas.DataFrame <-> engine columns."""

    def frame_to_columns(self, df, columns: Columns) -> ColumnarData:
        pid = df[columns.privacy_key].to_numpy()
        pk = _combine_key_columns(
            [df[c].to_numpy() for c in _as_list(columns.partition_key)])
        value = (df[columns.value].to_numpy()
                 if columns.value is not None else None)
        return ColumnarData(pid=pid, pk=pk, value=value)

    def columns_to_frame(self, data: Dict[str, np.ndarray]):
        import pandas as pd
        return pd.DataFrame(data)

    def column_names(self, df) -> List[str]:
        return list(df.columns)


class DictConverter(FrameConverter):
    """{name: np.ndarray} <-> engine columns."""

    def frame_to_columns(self, df, columns: Columns) -> ColumnarData:
        pk = _combine_key_columns(
            [np.asarray(df[c]) for c in _as_list(columns.partition_key)])
        value = (np.asarray(df[columns.value])
                 if columns.value is not None else None)
        return ColumnarData(pid=np.asarray(df[columns.privacy_key]),
                            pk=pk,
                            value=value)

    def columns_to_frame(self, data: Dict[str, np.ndarray]):
        return data

    def column_names(self, df) -> List[str]:
        return list(df.keys())


def _as_list(key: Union[str, Sequence[str]]) -> List[str]:
    return [key] if isinstance(key, str) else list(key)


def _combine_key_columns(arrays: List[np.ndarray]) -> np.ndarray:
    """One partition-key column from one or more key columns.

    A single column passes through (fully vectorized encoding downstream).
    Multiple columns become an object array of tuples — the composite key
    stays a real tuple so public keys and decoded output keys round-trip
    exactly.
    """
    if len(arrays) == 1:
        return arrays[0]
    out = np.empty(len(arrays[0]), dtype=object)
    out[:] = list(zip(*(a.tolist() for a in arrays)))
    return out


def _create_converter(df) -> FrameConverter:
    try:
        import pandas as pd
        if isinstance(df, pd.DataFrame):
            return PandasConverter()
    except ImportError:
        pass
    if isinstance(df, dict):
        return DictConverter()
    raise NotImplementedError(
        f"Frames of type {type(df)} are not supported; pass a pandas "
        f"DataFrame or a dict of numpy columns")


@dataclasses.dataclass
class _AggregationSpec:
    """One aggregation of the query (metric + input/output columns)."""
    metric: Metric
    input_column: Optional[str]
    output_column: Optional[str]
    min_value: Optional[float] = None
    max_value: Optional[float] = None


class Query:
    """A built DP query. Create through QueryBuilder.

    A Query is REUSABLE: repeat ``run_query`` calls on the same built
    query are the cheap path. The frame→columns conversion and the
    converter are computed once and cached on the query (each run still
    draws fresh noise under its own accountant), and the compiled
    epilogue executables are shared process-wide
    (ops/finalize.default_cache), so a repeat run of the same shape pays
    zero retraces. Session-bound queries (``QueryBuilder.on(session)``)
    go further and skip encode + sort entirely — see SERVING.md.
    """

    def __init__(self, df, columns: Columns,
                 metrics_output_columns: Dict[Metric, Optional[str]],
                 contribution_bounds: ContributionBounds,
                 public_partitions: Optional[Iterable],
                 session=None):
        self._df = df
        self._columns = columns
        self._metrics_output_columns = metrics_output_columns
        self._contribution_bounds = contribution_bounds
        self._public_partitions = public_partitions
        self._session = session
        # Per-query caches: filled on the first run, reused by repeat
        # runs of the same built query (the conversion is by far the
        # dominant host cost of a repeat run on large frames).
        self._cached_converter: Optional[FrameConverter] = None
        self._cached_data = None
        self.conversions = 0  # test/bench hook: frame→columns passes run

    def _build_params(self, noise_kind: NoiseKind) -> "agg.AggregateParams":
        return agg.AggregateParams(
            noise_kind=noise_kind,
            metrics=list(self._metrics_output_columns.keys()),
            max_partitions_contributed=self._contribution_bounds.
            max_partitions_contributed,
            max_contributions_per_partition=self._contribution_bounds.
            max_contributions_per_partition,
            min_value=self._contribution_bounds.min_value,
            max_value=self._contribution_bounds.max_value)

    def run_query(self,
                  budget: Budget,
                  noise_kind: NoiseKind = NoiseKind.LAPLACE,
                  engine: str = "jax",
                  seed: int = 0,
                  tenant: Optional[str] = None):
        """Runs the query and returns a frame of the input's kind.

        engine: "jax" (columnar TPU engine, default) or "local" (host
          oracle, DPEngine over LocalBackend). Session-bound queries run
          on the jax engine only.
        tenant: for session-bound queries, charges the budget to that
          tenant's ledger and routes the release through its
          at-most-once journal (DatasetSession.register_tenant).
        """
        params = self._build_params(noise_kind)
        if self._session is not None:
            if engine != "jax":
                raise ValueError(
                    "session-bound queries run on the resident jax "
                    "engine; engine='local' needs the raw frame")
            result = self._session.query(params,
                                         epsilon=budget.epsilon,
                                         delta=budget.delta,
                                         seed=seed,
                                         tenant=tenant)
            converter = self._session.frame_meta["converter"]
            return self._rows_to_frame(converter, list(result))
        if tenant is not None:
            raise ValueError(
                "tenant budgets need a session-bound query "
                "(QueryBuilder.on(session))")
        converter = self._cached_converter
        if converter is None:
            converter = self._cached_converter = _create_converter(self._df)
        accountant = budget_accounting.NaiveBudgetAccountant(
            total_epsilon=budget.epsilon, total_delta=budget.delta)
        public = (list(self._public_partitions)
                  if self._public_partitions is not None else None)
        data = self._cached_data
        if data is None:
            data = self._cached_data = converter.frame_to_columns(
                self._df, self._columns)
            self.conversions += 1

        if engine == "jax":
            from pipelinedp_tpu import jax_engine
            eng = jax_engine.JaxDPEngine(accountant, seed=seed)
            result = eng.aggregate(data, params, public_partitions=public)
            accountant.compute_budgets()
            rows = list(result)
        elif engine == "local":
            from pipelinedp_tpu import dp_engine
            from pipelinedp_tpu.backends import LocalBackend
            eng = dp_engine.DPEngine(accountant, LocalBackend())
            value_col = (data.value if data.value is not None else
                         np.zeros(len(data.pk)))
            row_iter = list(zip(data.pid.tolist(), data.pk.tolist(),
                                np.asarray(value_col).tolist()))
            extractors = DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
            result = eng.aggregate(row_iter, params, extractors,
                                   public_partitions=public)
            accountant.compute_budgets()
            rows = list(result)
        else:
            raise ValueError(f"Unknown engine {engine!r}; use 'jax' or "
                             f"'local'")
        return self._rows_to_frame(converter, rows)

    def _rows_to_frame(self, converter: FrameConverter, rows):
        key_columns = _as_list(self._columns.partition_key)
        name_map = {}  # engine metric name -> output column
        for metric, output_column in self._metrics_output_columns.items():
            engine_name = _metric_output_name(metric)
            name_map[engine_name] = output_column or engine_name
        out: Dict[str, list] = {c: [] for c in key_columns}
        for name in name_map.values():
            out[name] = []
        for pk, metrics_tuple in rows:
            if len(key_columns) == 1:
                out[key_columns[0]].append(pk)
            else:
                for col, part in zip(key_columns, pk):
                    out[col].append(part)
            for engine_name, value in metrics_tuple._asdict().items():
                if engine_name in name_map:
                    out[name_map[engine_name]].append(value)
        return converter.columns_to_frame(
            {name: np.asarray(vals) for name, vals in out.items()})


class _SessionColumns:
    """Column-name view of a resident session for QueryBuilder
    validation (the session holds no frame to convert — only the names
    it was ingested with)."""

    def __init__(self, column_names: List[str]):
        self._column_names = list(column_names)

    def column_names(self, df) -> List[str]:
        return list(self._column_names)


def _metric_output_name(metric: Metric) -> str:
    if metric.is_percentile:
        # Must match QuantileCombiner.metrics_names formatting exactly
        # (combiners.py), e.g. percentile_90 but percentile_99_5.
        p = metric.parameter
        int_p = int(round(p))
        text = str(int_p) if int_p == p else str(p).replace(".", "_")
        return f"percentile_{text}"
    return metric.name.lower()


class QueryBuilder:
    """Builds DP queries over a pandas DataFrame or a dict of columns.

    Builder pattern — every method except build_query returns self:

        query = (QueryBuilder(df, "user_id")
                 .groupby("day", max_groups_contributed=3,
                          max_contributions_per_group=1)
                 .count()
                 .sum("spent_money", min_value=0, max_value=100)
                 .mean("spent_money")
                 .build_query())
        result = query.run_query(Budget(epsilon=1, delta=1e-6))
    """

    def __init__(self, df, privacy_unit_column: str):
        self._converter = _create_converter(df)
        if privacy_unit_column not in self._converter.column_names(df):
            raise ValueError(
                f"Column {privacy_unit_column} is not present in the frame")
        self._df = df
        self._privacy_unit_column = privacy_unit_column
        self._session = None
        self._by: Optional[Union[str, Sequence[str]]] = None
        self._public_keys = None
        self._aggregations_specs: List[_AggregationSpec] = []
        self._max_partitions_contributed: Optional[int] = None
        self._max_contributions_per_partition: Optional[int] = None

    @classmethod
    def on(cls, session) -> "QueryBuilder":
        """Builds queries against a resident DatasetSession instead of a
        frame (serving.DatasetSession.from_frame; SERVING.md) — L5 user
        code stays declarative while repeat queries skip the encode +
        sort + transfer phases:

            session = DatasetSession.from_frame(df, "user_id", "day",
                                                "spent_money")
            result = (QueryBuilder.on(session)
                      .groupby("day", max_groups_contributed=3,
                               max_contributions_per_group=1)
                      .count().sum("spent_money", min_value=0,
                                   max_value=100)
                      .build_query().run_query(Budget(1.0, 1e-6)))

        The groupby column(s) and the value column must be the ones the
        session was ingested with (the sorted wire is fixed per
        session); contribution bounds and budgets stay per-query.
        """
        meta = session.frame_meta
        if meta is None:
            raise ValueError(
                "QueryBuilder.on needs a session created with "
                "DatasetSession.from_frame (the frame column binding is "
                "fixed at ingest)")
        builder = cls.__new__(cls)
        builder._converter = _SessionColumns(meta["column_names"])
        builder._df = None
        builder._privacy_unit_column = meta["privacy_unit_column"]
        builder._session = session
        builder._by = None
        builder._public_keys = None
        builder._aggregations_specs = []
        builder._max_partitions_contributed = None
        builder._max_contributions_per_partition = None
        return builder

    def groupby(self,
                by: Union[str, Sequence[str]],
                *,
                max_groups_contributed: int,
                max_contributions_per_group: int,
                public_keys: Optional[Iterable[Any]] = None) -> "QueryBuilder":
        """Sets the partition key column(s) and the contribution bounds.

        With public_keys the output keys coincide exactly with the given
        keys (missing ones get noise-only values); otherwise keys are
        selected with DP.
        """
        if self._by is not None:
            raise ValueError("groupby can be called only once")
        names = self._converter.column_names(self._df)
        for column in _as_list(by):
            if column not in names:
                raise ValueError(
                    f"Column {column} is not present in the frame")
        if self._session is not None:
            meta = self._session.frame_meta
            if _as_list(by) != meta["partition_key"]:
                raise ValueError(
                    f"session was ingested grouped by "
                    f"{meta['partition_key']}; a different groupby "
                    f"({_as_list(by)}) cannot reuse its sorted wire — "
                    f"ingest a second session for it")
            session_public = self._session.public_partitions
            if public_keys is not None:
                if (session_public is None
                        or list(public_keys) != session_public):
                    raise ValueError(
                        "public_keys differ from the session's: the "
                        "public filter is fixed at ingest")
            elif session_public is not None:
                raise ValueError(
                    "the session was ingested with public keys; pass the "
                    "same public_keys to groupby")
        self._by = by
        self._max_partitions_contributed = max_groups_contributed
        self._max_contributions_per_partition = max_contributions_per_group
        self._public_keys = public_keys
        return self

    def count(self, name: Optional[str] = None) -> "QueryBuilder":
        return self._add_aggregation(
            _AggregationSpec(metric=Metrics.COUNT,
                             input_column=None,
                             output_column=name))

    def privacy_id_count(self, name: Optional[str] = None) -> "QueryBuilder":
        return self._add_aggregation(
            _AggregationSpec(metric=Metrics.PRIVACY_ID_COUNT,
                             input_column=None,
                             output_column=name))

    def sum(self,
            column: str,
            *,
            min_value: Optional[float] = None,
            max_value: Optional[float] = None,
            name: Optional[str] = None) -> "QueryBuilder":
        return self._add_aggregation(
            _AggregationSpec(metric=Metrics.SUM,
                             input_column=column,
                             output_column=name,
                             min_value=min_value,
                             max_value=max_value))

    def mean(self,
             column: str,
             *,
             min_value: Optional[float] = None,
             max_value: Optional[float] = None,
             name: Optional[str] = None) -> "QueryBuilder":
        return self._add_aggregation(
            _AggregationSpec(metric=Metrics.MEAN,
                             input_column=column,
                             output_column=name,
                             min_value=min_value,
                             max_value=max_value))

    def variance(self,
                 column: str,
                 *,
                 min_value: Optional[float] = None,
                 max_value: Optional[float] = None,
                 name: Optional[str] = None) -> "QueryBuilder":
        return self._add_aggregation(
            _AggregationSpec(metric=Metrics.VARIANCE,
                             input_column=column,
                             output_column=name,
                             min_value=min_value,
                             max_value=max_value))

    def percentile(self,
                   column: str,
                   percentile: float,
                   *,
                   min_value: Optional[float] = None,
                   max_value: Optional[float] = None,
                   name: Optional[str] = None) -> "QueryBuilder":
        return self._add_aggregation(
            _AggregationSpec(metric=Metrics.PERCENTILE(percentile),
                             input_column=column,
                             output_column=name,
                             min_value=min_value,
                             max_value=max_value))

    def build_query(self) -> Query:
        self._check_by()
        if not self._aggregations_specs:
            raise ValueError(
                "No aggregations in the query. Call count, sum, mean etc")
        metrics = [spec.metric for spec in self._aggregations_specs]
        if len(set(metrics)) != len(metrics):
            raise ValueError("Each aggregation can be added only once.")
        input_column = self._get_input_column()
        min_value, max_value = self._get_value_caps()
        contribution_bounds = ContributionBounds(
            max_partitions_contributed=self._max_partitions_contributed,
            max_contributions_per_partition=self.
            _max_contributions_per_partition,
            min_value=min_value,
            max_value=max_value)
        if self._session is not None and input_column is not None:
            session_value = self._session.frame_meta["value_column"]
            if input_column != session_value:
                raise ValueError(
                    f"session was ingested with value column "
                    f"{session_value!r}; aggregating {input_column!r} "
                    f"needs a session ingested over that column")
        metric_to_output_column = dict(
            (spec.metric, spec.output_column)
            for spec in self._aggregations_specs)
        return Query(self._df,
                     Columns(self._privacy_unit_column, self._by,
                             input_column), metric_to_output_column,
                     contribution_bounds, self._public_keys,
                     session=self._session)

    def _add_aggregation(self, spec: _AggregationSpec) -> "QueryBuilder":
        self._check_by()
        if spec.input_column is not None:
            if spec.input_column not in self._converter.column_names(
                    self._df):
                raise ValueError(
                    f"Column {spec.input_column} is not present in the frame")
        self._aggregations_specs.append(spec)
        return self

    def _check_by(self) -> None:
        if self._by is None:
            raise NotImplementedError(
                "Global aggregations are not implemented yet. Call groupby")

    def _get_input_column(self) -> Optional[str]:
        input_columns = [
            spec.input_column for spec in self._aggregations_specs
            if spec.input_column is not None
        ]
        if len(set(input_columns)) > 1:
            raise NotImplementedError(
                f"Aggregation of only one column is supported, but "
                f"{input_columns} given")
        return input_columns[0] if input_columns else None

    def _get_value_caps(self) -> Tuple[Optional[float], Optional[float]]:
        metrics = set(spec.metric for spec in self._aggregations_specs)
        needs_caps = metrics.difference(
            [Metrics.COUNT, Metrics.PRIVACY_ID_COUNT])
        if not needs_caps:
            return None, None
        min_values = [
            spec.min_value for spec in self._aggregations_specs
            if spec.min_value is not None
        ]
        max_values = [
            spec.max_value for spec in self._aggregations_specs
            if spec.max_value is not None
        ]
        if not min_values or not max_values:
            raise ValueError("min_value and max_value must be given at least "
                             "once as arguments of sum or mean")
        if min(min_values) != max(min_values) or (min(max_values) !=
                                                  max(max_values)):
            raise ValueError("If min_value and max_value provided multiple "
                             "times they must be the same")
        return min_values[0], max_values[0]
