"""Row -> (privacy_id, partition_key, value) projection specs.

Parity: pipeline_dp/data_extractors.py (reference: data_extractors.py:5-37).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Any, Optional


@dataclasses.dataclass
class DataExtractors:
    """Functions projecting an input row onto the three DP-relevant columns.

    ``privacy_id_extractor`` maps a row to the unit of privacy (e.g. user id),
    ``partition_extractor`` to the group-by key, ``value_extractor`` to the
    numeric value being aggregated (may be None for COUNT-only pipelines).
    """
    privacy_id_extractor: Optional[Callable[[Any], Any]] = None
    partition_extractor: Optional[Callable[[Any], Any]] = None
    value_extractor: Optional[Callable[[Any], Any]] = None


@dataclasses.dataclass
class PreAggregateExtractors:
    """Extractors for pre-aggregated input rows.

    Pre-aggregated rows carry ``(partition_key, (count, sum, n_partitions,
    n_contributions))`` — the output format of ``analysis.pre_aggregation``.
    Parity: data_extractors.py:18-37.
    """
    partition_extractor: Callable[[Any], Any]
    preaggregate_extractor: Callable[[Any], Any]


@dataclasses.dataclass
class MultiValueDataExtractors(DataExtractors):
    """Extractors producing a tuple of values per row (multi-column SUM).

    Each extractor in ``value_extractors`` yields one scalar; rows are mapped
    to tuples. Mirrors the multi-column aggregation support of the reference
    dataframes API (dataframes.py:167-244).
    """
    value_extractors: tuple = ()

    def __post_init__(self):
        if self.value_extractors and self.value_extractor is None:
            extractors = tuple(self.value_extractors)
            self.value_extractor = lambda row: tuple(
                e(row) for e in extractors)
