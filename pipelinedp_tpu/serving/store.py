"""SessionStore: the durable, crash-recoverable home of serving sessions.

PR 9's ``DatasetSession`` made warm queries cheap but kept everything in
process memory: a restart re-paid full ingest for every resident
dataset, and tenant release/budget history died with the process. This
module is the durability rung under the serving fleet (SERVING.md
"Fleet operation"):

  * ``DatasetSession.save(store)`` spills the session's ``ResidentWire``
    — sorted chunk slab, per-bucket counts, base wire format,
    ``resident_fingerprint`` — plus the bound-cache entries and the
    tenant registrations to an on-disk session directory;
  * ``SessionStore.open(name)`` re-hydrates a session after process
    death whose warm queries are **bit-identical** to the original
    session (and therefore to cold runs): the slab bytes are
    digest-validated chunk by chunk against the save-time digests, and
    the reconstructed format/counts are validated by recomputing the
    wire fingerprint;
  * tenant release journals and budget ledgers live on fsync'd WALs
    (runtime/journal.py) under the session directory, so cross-restart
    release replays are refused and ledger spend survives the crash.

Torn-write discipline (the ``FileCheckpointStore`` rules): every payload
file is written tmp + fsync + atomic rename, and the manifest — the
only entry point — is renamed into place *last*, so a crash mid-save
leaves either the previous complete session or no session, never a half
one. Corruption detection is layered by blast radius: a corrupted wire
payload refuses to open (``SessionCorruptError`` — the store must never
serve wrong bits), while a corrupted bound-cache entry is merely
dropped — the accumulators recompute exactly via kernel replay, so the
failure costs a replay, not correctness.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import profiler
from pipelinedp_tpu.ops import encoding, streaming, wirecodec
from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib
from pipelinedp_tpu.runtime import journal as journal_lib

# Default store root (README "Tuning knobs" + SERVING.md): sessions live
# under ``$PIPELINEDP_TPU_SESSION_DIR/<name>/``.
SESSION_DIR_ENV = "PIPELINEDP_TPU_SESSION_DIR"
DEFAULT_ROOT = ".pdp-sessions"

FORMAT_VERSION = 1
MANIFEST_FILE = "manifest.json"
WIRE_FILE = "wire.npz"
BOUND_DIR = "bound"
TENANT_DIR = "tenants"
# Live-session layout (serving/live.py; see the live section below).
APPEND_WAL_FILE = "append.wal"
EPOCH_DIR = "epochs"
DEADLETTER_DIR = "deadletter"
SCHEDULE_DIR = "schedule"

# Profiler event counters (profiler.count_event / event_count):
EVENT_SAVES = "serving/store_saves"
EVENT_OPENS = "serving/store_opens"
# Spilled bound-cache entries dropped on load because their content
# digest no longer matched (bit rot / torn write): the query that wants
# them recomputes via kernel replay instead of crashing or serving
# wrong bits.
EVENT_BOUND_DROPPED = "serving/bound_cache_corrupt_dropped"


class SessionStoreError(RuntimeError):
    """Base of the session store's typed failures."""


class SessionNotFoundError(SessionStoreError):
    """No (complete) session of that name exists in the store."""


class SessionCorruptError(SessionStoreError):
    """A stored wire payload fails its digests: the store refuses to
    re-hydrate rather than serve bits that differ from what was saved."""


def default_root() -> str:
    return os.environ.get(SESSION_DIR_ENV) or DEFAULT_ROOT


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename: the file is either the old version or the
    complete new one, never a torn mix."""
    parent = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    import io
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _chunk_digest(row: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(row).tobytes()) \
        .hexdigest()[:16]


def _key_to_json(key: Tuple) -> Any:
    """Bound-cache keys are canonical tuples of scalars (see
    DatasetSession._canonical); JSON encodes tuples as lists."""
    if isinstance(key, tuple):
        return [_key_to_json(k) for k in key]
    return key


def _key_from_json(obj: Any) -> Any:
    """Inverse of _key_to_json: every list becomes a tuple again, so the
    loaded key compares equal to the live one that was saved."""
    if isinstance(obj, list):
        return tuple(_key_from_json(o) for o in obj)
    return obj


def _encode_vocab(vocab: encoding.Vocabulary
                  ) -> Tuple[dict, Optional[np.ndarray]]:
    """(manifest meta, optional array payload) for the pk vocabulary.

    Scalar key sets round-trip as a numpy array inside wire.npz
    (digested with the rest of the payload); tuple keys (multi-column
    partition keys) and anything numpy would store as dtype=object go
    through JSON in the manifest."""
    keys = vocab.keys
    arr = np.asarray(keys) if keys else np.zeros(0, dtype=np.int64)
    if arr.dtype != object and arr.ndim == 1:
        return {"kind": "array"}, arr
    tuples = bool(keys) and isinstance(keys[0], tuple)
    try:
        payload = [list(k) if isinstance(k, tuple) else k for k in keys]
        json.dumps(payload)
    except TypeError as exc:
        raise SessionStoreError(
            f"partition-key vocabulary is not serializable (sample key "
            f"{keys[0]!r}); a durable session needs JSON- or "
            f"numpy-representable partition keys") from exc
    return {"kind": "json", "keys": payload, "tuples": tuples}, None


def _decode_vocab(meta: dict, arr: Optional[np.ndarray]
                  ) -> encoding.Vocabulary:
    if meta["kind"] == "array":
        return encoding.Vocabulary.from_unique(arr)
    keys = meta["keys"]
    if meta["tuples"]:
        keys = [tuple(k) for k in keys]
    return encoding.Vocabulary(keys)


def _result_arrays(result) -> Tuple[Tuple[np.ndarray, ...],
                                    Optional[np.ndarray]]:
    """(accs arrays, qhist) of one bound-cache result (accs alone, or
    (accs, qhist) on the quantile path)."""
    if isinstance(result, tuple) and not hasattr(result, "_fields"):
        accs, qhist = result
        return (tuple(np.asarray(a) for a in accs),
                None if qhist is None else np.asarray(qhist))
    return tuple(np.asarray(a) for a in result), None


def _bound_entry_digest(key_json: str, accs, qhist) -> str:
    return checkpoint_lib.content_digest(
        key_json, *(accs + ((qhist,) if qhist is not None else ())))


class SessionStore:
    """A directory of durable serving sessions (module docstring).

    One instance may back many sessions and many SessionManagers; all
    methods take the session name. Paths under the store are stable, so
    ``FileReleaseJournal``/ledger WALs handed out for a session keep
    working across saves.
    """

    def __init__(self, root: Optional[str] = None):
        self._root = root if root is not None else default_root()
        os.makedirs(self._root, exist_ok=True)

    @property
    def root(self) -> str:
        return self._root

    @staticmethod
    def _safe(name: str) -> str:
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", name)
        if not safe or safe in (".", ".."):
            raise SessionStoreError(f"unusable session name {name!r}")
        return safe

    def path(self, name: str) -> str:
        return os.path.join(self._root, self._safe(name))

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.path(name), MANIFEST_FILE))

    def names(self) -> List[str]:
        """Names of complete (manifest-bearing) sessions in the store."""
        out = []
        for entry in sorted(os.listdir(self._root)):
            if os.path.exists(os.path.join(self._root, entry,
                                           MANIFEST_FILE)):
                out.append(entry)
        return out

    def delete(self, name: str) -> None:
        """Drops a stored session (manifest first, so a crash mid-delete
        leaves an incomplete — and therefore invisible — directory)."""
        import shutil
        path = self.path(name)
        manifest = os.path.join(path, MANIFEST_FILE)
        if os.path.exists(manifest):
            os.unlink(manifest)
        if os.path.exists(path):
            shutil.rmtree(path, ignore_errors=True)

    # -- per-tenant durable state paths ----------------------------------

    def tenant_release_path(self, name: str, tenant_id: str) -> str:
        return os.path.join(self.path(name), TENANT_DIR,
                            f"{self._safe(tenant_id)}.release.wal")

    def tenant_ledger_path(self, name: str, tenant_id: str) -> str:
        return os.path.join(self.path(name), TENANT_DIR,
                            f"{self._safe(tenant_id)}.ledger.wal")

    def lease_path(self, name: str) -> str:
        """The session's single-writer lease file (serving/fleet.py):
        writable opens acquire it; its fencing token rides every WAL
        append so a superseded writer is refused at the journal."""
        from pipelinedp_tpu.serving import fleet as fleet_lib
        return os.path.join(self.path(name), fleet_lib.LEASE_FILE)

    def _acquire_lease(self, name: str, lease_ttl_s, force_lease: bool):
        """The writable-open gate: takes the session's single-writer
        lease (raising LeaseHeldError when another live process holds
        it) so two processes can never interleave appends to one
        session directory."""
        from pipelinedp_tpu.serving import fleet as fleet_lib
        return fleet_lib.SessionLease.acquire(
            self.lease_path(name), ttl_s=lease_ttl_s, force=force_lease)

    def audit_path(self, name: str) -> str:
        """The session's release-audit-trail WAL (obs/audit.py): rides
        the same fsync'd JsonlWal discipline as the tenant journals, so
        committed query outcomes survive SIGKILL and replay exactly on
        reopen."""
        return os.path.join(self.path(name), "audit.wal")

    def flight_dir(self) -> str:
        """Where the process flight recorder (obs/flight.py) spools and
        dumps for store-bound sessions — next to the WALs, so a
        SIGKILL'd or wedged serving process leaves its post-mortem in
        the same place its durable state lives. Store-scoped (not
        per-session): the recorder is process-global."""
        return os.path.join(self._root, "flight")

    # -- save ------------------------------------------------------------

    def save(self, session) -> str:
        """Persists ``session`` (DatasetSession.save delegates here).

        Layout under ``<root>/<name>/``::

            wire.npz       slab + counts + n_uniq (+ vocab array)
            bound/*.npz    spilled bound-cache entries, content-digested
            tenants/*.wal  per-tenant release + ledger WALs (fsync'd)
            manifest.json  digests + metadata — written LAST, atomically

        Saving is idempotent and incremental: the wire payload is
        written once (it is immutable), bound entries are content-
        addressed, and only the manifest is rewritten.
        """
        name = session.name
        path = self.path(name)
        os.makedirs(path, exist_ok=True)
        os.makedirs(os.path.join(path, BOUND_DIR), exist_ok=True)
        os.makedirs(os.path.join(path, TENANT_DIR), exist_ok=True)

        wire: streaming.ResidentWire = session._wire
        if not wire.loaded:
            raise SessionStoreError(
                f"session {name!r} is spilled; re-hydrate before saving "
                f"(the store already holds its latest saved state)")
        vocab_meta, vocab_arr = _encode_vocab(session._pk_vocab)

        wire_path = os.path.join(path, WIRE_FILE)
        chunk_digests = [_chunk_digest(wire.slab[i])
                         for i in range(wire.k)]
        aux_arrays = [wire.counts, wire.n_uniq]
        if vocab_arr is not None:
            aux_arrays.append(vocab_arr)
        aux_digest = checkpoint_lib.content_digest("aux", *aux_arrays)
        # The wire payload is immutable per handle, so a re-save skips
        # it — unless the name was previously used for a DIFFERENT
        # handle (fingerprint mismatch, or no readable manifest to tell):
        # then the stale payload must be replaced, not trusted.
        write_wire = not os.path.exists(wire_path)
        if not write_wire:
            try:
                write_wire = (self._read_manifest(name)["fingerprint"]
                              != wire.fingerprint)
            except SessionStoreError:
                write_wire = True
        if write_wire:
            arrays = {"slab": wire.slab, "counts": wire.counts,
                      "n_uniq": wire.n_uniq}
            if vocab_arr is not None:
                arrays["vocab_keys"] = vocab_arr
            _atomic_write(wire_path, _npz_bytes(arrays))

        # Bound-cache entries: content-addressed npz files, digested so
        # re-hydration can tell bit rot from a valid accumulator and
        # fall back to kernel replay.
        bound_entries = []
        with session._lock:
            cache_snapshot = [(key, entry.result, entry.nbytes)
                              for key, entry in session._bound_cache.items()]
        for key, result, nbytes in cache_snapshot:
            key_json = json.dumps(_key_to_json(key), sort_keys=False)
            accs, qhist = _result_arrays(result)
            digest = _bound_entry_digest(key_json, accs, qhist)
            fname = hashlib.sha256(key_json.encode()).hexdigest()[:24] \
                + ".npz"
            fpath = os.path.join(path, BOUND_DIR, fname)
            if not os.path.exists(fpath):
                arrays = {f"accs_{i}": a for i, a in enumerate(accs)}
                if qhist is not None:
                    arrays["qhist"] = qhist
                _atomic_write(fpath, _npz_bytes(arrays))
            bound_entries.append({
                "file": fname,
                "key": _key_to_json(key),
                "has_qhist": qhist is not None,
                "digest": digest,
                "nbytes": int(nbytes),
            })

        # Tenants: migrate in-memory journals/ledgers onto durable WALs
        # under the store, then record the registrations.
        tenants = []
        with session._lock:
            tenant_items = list(session._tenants.items())
        for tenant_id, state in tenant_items:
            state.release_journal = self._migrate_release_journal(
                name, tenant_id, state.release_journal)
            state.ledger = self._migrate_ledger(name, tenant_id,
                                                state.ledger)
            tenants.append(self._tenant_manifest_entry(
                tenant_id, state.ledger, state.release_journal))

        fmt = wire.fmt
        manifest = {
            "version": FORMAT_VERSION,
            "name": name,
            "fingerprint": wire.fingerprint,
            "data_digest": wire.data_digest,
            "n_rows": int(wire.n_rows),
            "num_partitions": int(wire.num_partitions),
            "n_dev": int(wire.n_dev),
            "max_run": int(wire.max_run),
            "fmt": {
                "bytes_pid": fmt.bytes_pid,
                "bits_pk": fmt.bits_pk,
                "cap": fmt.cap,
                "ucap": fmt.ucap,
                "pid_mode": fmt.pid_mode,
                "bits_pid": fmt.bits_pid,
                "tile_rows": fmt.tile_rows,
                "tile_slack": fmt.tile_slack,
                "sort_value_narrow": fmt.sort_value_narrow,
                "value": {
                    "mode": fmt.value.mode,
                    "bits": fmt.value.bits,
                    "lo": fmt.value.lo,
                    "scale": fmt.value.scale,
                },
            },
            "chunk_digests": chunk_digests,
            "aux_digest": aux_digest,
            "vocab": vocab_meta,
            "public_partitions": (
                None if session._public is None else
                [type(session)._canonical(p) for p in session._public]),
            "knobs": {
                "secure_host_noise": session._secure_host_noise,
                "segment_sort": session._segment_sort,
                "compact_merge": session._compact_merge,
            },
            "bound_entries": bound_entries,
            "tenants": tenants,
        }
        _atomic_write(os.path.join(path, MANIFEST_FILE),
                      json.dumps(manifest, indent=1).encode())
        session._store_binding = (self, name)
        session._bind_audit()
        profiler.count_event(EVENT_SAVES)
        return path

    @staticmethod
    def _tenant_manifest_entry(tenant_id, ledger, release_journal) -> dict:
        entry = {"id": tenant_id,
                 "total_epsilon": ledger.total_epsilon,
                 "total_delta": ledger.total_delta}
        if ledger.window_epsilon is not None \
                or ledger.window_delta is not None:
            entry["window_epsilon"] = ledger.window_epsilon
            entry["window_delta"] = ledger.window_delta
        path = getattr(release_journal, "_path", None)
        if path is not None:
            entry["release_journal_path"] = os.path.abspath(path)
        return entry

    def _migrate_release_journal(self, name, tenant_id, journal):
        """In-memory tenant journals become store-local FileReleaseJournals
        with the committed records replayed in order; already-durable
        journals are kept wherever the caller put them."""
        if isinstance(journal, journal_lib.FileReleaseJournal):
            return journal
        durable = journal_lib.FileReleaseJournal(
            self.tenant_release_path(name, tenant_id))
        for record in journal.records:
            if not durable.has(record.token):
                durable.commit(record.token, kind=record.kind)
        return durable

    def _migrate_ledger(self, name, tenant_id,
                        ledger: budget_accounting.TenantBudgetLedger):
        """In-memory ledgers become WAL-backed ones with every committed
        charge (and refund) replayed; WAL-backed ledgers pass through."""
        if ledger._wal is not None:
            return ledger
        wal = journal_lib.FileReleaseJournal(
            self.tenant_ledger_path(name, tenant_id))
        durable = budget_accounting.TenantBudgetLedger(
            ledger.tenant_id, ledger.total_epsilon, ledger.total_delta,
            wal=wal, window_epsilon=ledger.window_epsilon,
            window_delta=ledger.window_delta)
        refunded = ledger.refunded_indices
        for charge in ledger.charges:
            replayed = durable.charge(charge.epsilon, charge.delta,
                                      note=charge.note,
                                      window=charge.window)
            # Refund immediately so a replayed prefix never holds MORE
            # live budget than the original ledger ever did (refunding
            # only at the end could spuriously overdraw when a later
            # charge reused budget an earlier refund freed).
            if charge.index in refunded:
                durable.refund(replayed)
        return durable

    def record_tenant(self, name: str, tenant_id: str, total_epsilon: float,
                      total_delta: float, release_journal, *,
                      window_epsilon: Optional[float] = None,
                      window_delta: Optional[float] = None) -> None:
        """Appends one tenant registration to an existing manifest
        atomically (so a crash between register_tenant and the next full
        save still reattaches the tenant on reopen)."""
        manifest = self._read_manifest(name)
        ledger = budget_accounting.TenantBudgetLedger(
            tenant_id, total_epsilon, total_delta,
            window_epsilon=window_epsilon, window_delta=window_delta)
        entry = self._tenant_manifest_entry(tenant_id, ledger,
                                            release_journal)
        tenants = [t for t in manifest["tenants"] if t["id"] != tenant_id]
        tenants.append(entry)
        manifest["tenants"] = tenants
        _atomic_write(os.path.join(self.path(name), MANIFEST_FILE),
                      json.dumps(manifest, indent=1).encode())

    # -- load ------------------------------------------------------------

    def _read_manifest(self, name: str) -> dict:
        path = os.path.join(self.path(name), MANIFEST_FILE)
        if not os.path.exists(path):
            raise SessionNotFoundError(
                f"no session {name!r} in store {self._root!r}")
        try:
            with open(path, "rb") as f:
                manifest = json.load(f)
        except ValueError as exc:
            raise SessionCorruptError(
                f"session {name!r}: unreadable manifest ({exc})") from exc
        if manifest.get("version") != FORMAT_VERSION:
            raise SessionStoreError(
                f"session {name!r}: manifest version "
                f"{manifest.get('version')!r} (this build reads "
                f"{FORMAT_VERSION})")
        return manifest

    def _load_wire_arrays(self, name: str, manifest: dict) -> dict:
        path = os.path.join(self.path(name), WIRE_FILE)
        try:
            with np.load(path, allow_pickle=False) as data:
                arrays = {k: np.array(data[k]) for k in data.files}
        except (OSError, ValueError, KeyError,
                zipfile.BadZipFile) as exc:
            raise SessionCorruptError(
                f"session {name!r}: unreadable wire payload ({exc})"
            ) from exc
        slab = arrays.get("slab")
        if slab is None or len(slab) != len(manifest["chunk_digests"]):
            raise SessionCorruptError(
                f"session {name!r}: wire payload does not match the "
                f"manifest chunk schedule")
        for i, expected in enumerate(manifest["chunk_digests"]):
            if _chunk_digest(slab[i]) != expected:
                raise SessionCorruptError(
                    f"session {name!r}: wire chunk {i} fails its content "
                    f"digest — the spilled slab is corrupt; refusing to "
                    f"serve bits that differ from what was saved")
        aux = [arrays["counts"], arrays["n_uniq"]]
        if "vocab_keys" in arrays:
            aux.append(arrays["vocab_keys"])
        if checkpoint_lib.content_digest("aux", *aux) \
                != manifest["aux_digest"]:
            raise SessionCorruptError(
                f"session {name!r}: wire metadata (counts / vocabulary) "
                f"fails its content digest")
        return arrays

    def _load_bound_entries(self, name: str, manifest: dict
                            ) -> List[Tuple[Tuple, Any]]:
        """Digest-validated bound-cache entries; corrupted ones are
        dropped (and counted) — the query that wants them recomputes
        via kernel replay, bit-identically."""
        from pipelinedp_tpu.ops import columnar
        out = []
        for entry in manifest["bound_entries"]:
            fpath = os.path.join(self.path(name), BOUND_DIR, entry["file"])
            key = _key_from_json(entry["key"])
            key_json = json.dumps(_key_to_json(key), sort_keys=False)
            try:
                with np.load(fpath, allow_pickle=False) as data:
                    n_accs = sum(1 for f in data.files
                                 if f.startswith("accs_"))
                    accs = tuple(np.array(data[f"accs_{i}"])
                                 for i in range(n_accs))
                    qhist = (np.array(data["qhist"])
                             if entry["has_qhist"] else None)
            except (OSError, ValueError, KeyError, zipfile.BadZipFile):
                accs = None
            if (accs is None or _bound_entry_digest(key_json, accs, qhist)
                    != entry["digest"]):
                logging.warning(
                    "pipelinedp_tpu serving store: bound-cache entry %s "
                    "of session %s is corrupt; dropping it (the query "
                    "recomputes via kernel replay)", entry["file"], name)
                profiler.count_event(EVENT_BOUND_DROPPED)
                continue
            result = columnar.PartitionAccumulators(*accs)
            out.append((key, (result, qhist) if entry["has_qhist"]
                        else result))
        return out

    def load_payload(self, name: str) -> Tuple[np.ndarray, list]:
        """(validated slab, bound entries) — the re-hydration path for a
        spilled session whose handle (metadata) is still in memory."""
        manifest = self._read_manifest(name)
        arrays = self._load_wire_arrays(name, manifest)
        return arrays["slab"], self._load_bound_entries(name, manifest)

    def _rebuild_wire(self, name: str, manifest: dict,
                      arrays: dict) -> streaming.ResidentWire:
        f = manifest["fmt"]
        fmt = wirecodec.WireFormat(
            bytes_pid=f["bytes_pid"], bits_pk=f["bits_pk"], cap=f["cap"],
            ucap=f["ucap"],
            value=wirecodec.ValuePlan(
                mode=f["value"]["mode"], bits=f["value"]["bits"],
                lo=f["value"]["lo"], scale=f["value"]["scale"]),
            pid_mode=f["pid_mode"], bits_pid=f["bits_pid"],
            tile_rows=f["tile_rows"], tile_slack=f["tile_slack"],
            sort_value_narrow=f["sort_value_narrow"])
        counts = np.asarray(arrays["counts"], dtype=np.int64)
        n_uniq = np.asarray(arrays["n_uniq"], dtype=np.int64)
        wire = streaming.ResidentWire(
            slab=np.ascontiguousarray(arrays["slab"]),
            counts=counts, n_uniq=n_uniq, fmt=fmt,
            max_run=manifest["max_run"],
            num_partitions=manifest["num_partitions"],
            n_rows=manifest["n_rows"], n_dev=manifest["n_dev"],
            data_digest=manifest["data_digest"],
            fingerprint=manifest["fingerprint"])
        # The chunk digests validated the slab bytes; recomputing the
        # resident fingerprint validates everything else (format,
        # counts, chunk count, source digest) against the save-time
        # identity.
        recomputed = wirecodec.resident_fingerprint(
            wire.k, fmt, counts, n_uniq, manifest["data_digest"])
        if recomputed != manifest["fingerprint"]:
            raise SessionCorruptError(
                f"session {name!r}: reconstructed wire fingerprint "
                f"{recomputed} does not match the saved "
                f"{manifest['fingerprint']} — manifest metadata is "
                f"corrupt")
        return wire

    def open(self, name: str, *, mesh=None, resident_bytes=None,
             epilogue_cache=None, read_only: bool = False,
             lease_ttl_s=None, force_lease: bool = False):
        """Re-hydrates a stored session.

        The returned DatasetSession serves warm queries bit-identical to
        the session that was saved (tests/serving_fleet_test.py and the
        serving kill harness pin this, single-device and mesh8), with
        every saved tenant reattached to its durable release journal and
        ledger WAL — a cross-restart release replay raises
        DoubleReleaseError, and spent budget stays spent.

        A writable open acquires the session's single-writer lease
        (``LeaseHeldError`` when another live process holds it — two
        writers interleaving one directory is the split this refuses).
        ``read_only=True`` opens a follower replica instead: no lease,
        no WAL handles (the audit trail stays in-memory and the saved
        tenants are NOT reattached — ledgers and release journals are
        single-writer state), and every mutating path refuses with
        SessionReadOnlyError.

        ``mesh`` must match the topology the wire was ingested for
        (n_dev buckets per chunk).
        """
        from pipelinedp_tpu.serving.session import DatasetSession

        manifest = self._read_manifest(name)
        if manifest.get("live"):
            raise SessionStoreError(
                f"session {name!r} is a live (streaming) session; its "
                f"stored wire is a point-in-time spill, not the epoch "
                f"log — reopen it with SessionStore.open_live so the "
                f"append WAL replays")
        n_dev = mesh.devices.size if mesh is not None else 1
        if manifest["n_dev"] != n_dev:
            raise ValueError(
                f"session {name!r} was ingested for n_dev="
                f"{manifest['n_dev']}; opening with n_dev={n_dev} cannot "
                f"replay it (pass the matching mesh)")
        lease = None
        if not read_only:
            lease = self._acquire_lease(name, lease_ttl_s, force_lease)
        try:
            arrays = self._load_wire_arrays(name, manifest)
            wire = self._rebuild_wire(name, manifest, arrays)
            vocab = _decode_vocab(manifest["vocab"],
                                  arrays.get("vocab_keys"))
            knobs = manifest["knobs"]
            session = DatasetSession._restore(
                wire, vocab,
                public_partitions=manifest["public_partitions"],
                mesh=mesh, name=manifest["name"],
                secure_host_noise=knobs["secure_host_noise"],
                segment_sort=knobs["segment_sort"],
                compact_merge=knobs["compact_merge"],
                resident_bytes=resident_bytes,
                epilogue_cache=epilogue_cache,
                store_binding=None if read_only else (self, name))
            for key, result in self._load_bound_entries(name, manifest):
                session._cache_insert(key, result)
            if read_only:
                # Late-bind the store WITHOUT _bind_audit: a follower
                # must never open append handles on the primary's WALs.
                session._store_binding = (self, name)
                session._read_only = True
            else:
                self._reattach_tenants(session, name, manifest)
                session._attach_lease(lease)
        except BaseException:
            if lease is not None:
                lease.release()
            raise
        profiler.count_event(EVENT_OPENS)
        return session

    def _reattach_tenants(self, session, name: str, manifest: dict) -> None:
        """Rebinds every manifest tenant to its durable ledger and
        release-journal WALs (shared by open and open_live)."""
        from pipelinedp_tpu.serving.session import TenantState
        for entry in manifest["tenants"]:
            release_path = entry.get(
                "release_journal_path",
                self.tenant_release_path(name, entry["id"]))
            state = TenantState(
                ledger=budget_accounting.TenantBudgetLedger(
                    entry["id"], entry["total_epsilon"],
                    entry["total_delta"],
                    wal=journal_lib.FileReleaseJournal(
                        self.tenant_ledger_path(name, entry["id"])),
                    window_epsilon=entry.get("window_epsilon"),
                    window_delta=entry.get("window_delta")),
                release_journal=journal_lib.FileReleaseJournal(
                    release_path))
            session._tenants[entry["id"]] = state

    # -- live (streaming append) sessions --------------------------------
    #
    # A live session keeps, next to the ordinary spill layout, the data
    # that makes append crash-exactly-once (serving/live.py):
    #
    #     append.wal          fsync'd WAL: one "append" record per
    #                         committed epoch (content digest + row
    #                         count + event time) and one "advance"
    #                         record per watermark advancement — the
    #                         record count IS the epoch counter, and
    #                         appending the record IS the commit point
    #     epochs/e<N>.npz     the raw micro-batch of epoch N, written
    #                         (tmp+fsync+rename) BEFORE its WAL record
    #     deadletter/*.npz    late batches under the "dead_letter"
    #                         policy, keyed by content digest
    #     schedule/<id>.wal   per-ReleaseSchedule outcome WALs
    #
    # manifest["live"] marks the session as live (record_live) so the
    # batch open() refuses it and open_live knows the window/watermark
    # configuration to rebuild.

    def append_wal_path(self, name: str) -> str:
        return os.path.join(self.path(name), APPEND_WAL_FILE)

    def epoch_path(self, name: str, epoch: int) -> str:
        return os.path.join(self.path(name), EPOCH_DIR, f"e{epoch}.npz")

    def save_epoch(self, name: str, epoch: int, pid, pk, value) -> str:
        """Durably writes one epoch's raw micro-batch (atomic; the WAL
        record that commits the epoch is appended only after this
        returns, so a crash in between leaves an orphan payload the
        next append simply overwrites)."""
        path = self.epoch_path(name, epoch)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        arrays = {"pid": np.asarray(pid), "pk": np.asarray(pk)}
        if value is not None:
            arrays["value"] = np.asarray(value)
        _atomic_write(path, _npz_bytes(arrays))
        return path

    def load_epoch(self, name: str, epoch: int, digest: str):
        """(pid, pk, value) of one committed epoch, digest-validated
        against the append-WAL record that committed it — a payload
        that fails its digest refuses (the live session must never
        fold rows that differ from what was committed)."""
        path = self.epoch_path(name, epoch)
        try:
            with np.load(path, allow_pickle=False) as data:
                pid = np.array(data["pid"])
                pk = np.array(data["pk"])
                value = (np.array(data["value"])
                         if "value" in data.files else None)
        except (OSError, ValueError, KeyError, zipfile.BadZipFile) as exc:
            raise SessionCorruptError(
                f"session {name!r}: epoch {epoch} payload is unreadable "
                f"({exc}); the append WAL committed it — refusing to "
                f"reopen without its rows") from exc
        if streaming.input_digest(pid, pk, value) != digest:
            raise SessionCorruptError(
                f"session {name!r}: epoch {epoch} payload fails the "
                f"content digest its append-WAL record committed; "
                f"refusing to fold rows that differ from what was "
                f"acknowledged")
        return pid, pk, value

    def deadletter_path(self, name: str, digest: str) -> str:
        return os.path.join(self.path(name), DEADLETTER_DIR,
                            f"{digest}.npz")

    def save_deadletter(self, name: str, digest: str, pid, pk,
                        value) -> str:
        """Persists one late batch under the dead-letter policy, keyed
        by content digest (idempotent: a re-submitted late batch
        overwrites its identical self)."""
        path = self.deadletter_path(name, digest)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        arrays = {"pid": np.asarray(pid), "pk": np.asarray(pk)}
        if value is not None:
            arrays["value"] = np.asarray(value)
        _atomic_write(path, _npz_bytes(arrays))
        return path

    def deadletter_digests(self, name: str) -> List[str]:
        """Content digests of the dead-lettered batches, sorted."""
        path = os.path.join(self.path(name), DEADLETTER_DIR)
        if not os.path.isdir(path):
            return []
        return sorted(f[:-len(".npz")] for f in os.listdir(path)
                      if f.endswith(".npz"))

    def schedule_path(self, name: str, schedule_id: str) -> str:
        return os.path.join(self.path(name), SCHEDULE_DIR,
                            f"{self._safe(schedule_id)}.wal")

    def record_live(self, name: str, meta: dict) -> None:
        """Atomically records (or updates) the manifest's live-session
        section — window/watermark configuration plus everything
        open_live needs that the append WAL does not carry."""
        manifest = self._read_manifest(name)
        manifest["live"] = meta
        _atomic_write(os.path.join(self.path(name), MANIFEST_FILE),
                      json.dumps(manifest, indent=1).encode())

    def open_live(self, name: str, *, mesh=None, resident_bytes=None,
                  epilogue_cache=None, read_only: bool = False,
                  lease_ttl_s=None, force_lease: bool = False):
        """Reopens a live session after process death: replays the
        append WAL, loads and digest-validates every committed epoch
        payload, and rebuilds the union wire — landing at exactly the
        epoch the WAL committed (N, or N+1 when the crash fell after
        the WAL append), bit-identical to a session that never died.
        See serving/live.py for the append/commit discipline.

        Writable opens take the single-writer lease FIRST — torn-tail
        truncation during WAL recovery is a write, and only the lease
        holder may perform it — then fence every WAL (append, tenant,
        schedule) with the lease's token. ``read_only=True`` is the hot
        follower: replay rides the truncation-free
        ``runtime.journal.read_records`` scanner, no lease, no WAL
        handles, tenants not reattached (serving/fleet.py)."""
        from pipelinedp_tpu.serving import live as live_lib

        manifest = self._read_manifest(name)
        if not manifest.get("live"):
            raise SessionStoreError(
                f"session {name!r} is not a live session; use "
                f"SessionStore.open")
        lease = None
        if not read_only:
            lease = self._acquire_lease(name, lease_ttl_s, force_lease)
        try:
            session = live_lib.LiveDatasetSession._reopen(
                self, name, manifest, mesh=mesh,
                resident_bytes=resident_bytes,
                epilogue_cache=epilogue_cache, read_only=read_only)
            if not read_only:
                self._reattach_tenants(session, name, manifest)
                session._attach_lease(lease)
        except BaseException:
            if lease is not None:
                lease.release()
            raise
        profiler.count_event(EVENT_OPENS)
        return session
