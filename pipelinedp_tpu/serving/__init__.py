"""Resident-dataset query serving: pay encode + sort once, serve many
DP queries per launch.

The production shape for "millions of users" (ROADMAP north star) is not
one batch job but many DP queries per day against the same dataset. This
package is the long-lived serving layer over the columnar engine:

  * :class:`DatasetSession` runs the wire pipeline ONCE — the SlabDriver
    streams the dataset through encode / per-bucket radix sort /
    transfer in retain-wire mode — and keeps the sorted wire chunks as a
    reusable handle (device-resident when they fit the placement's byte
    budget, host slab cache otherwise; ``PIPELINEDP_TPU_RESIDENT_BYTES``).
    Every subsequent query is kernel + fused epilogue only, bit-identical
    to the same query run cold.
  * :meth:`DatasetSession.query_batch` packs concurrent queries that
    share the sorted wire but differ in metric set / epsilon / clip
    bounds into ONE vmapped launch per chunk
    (``PIPELINEDP_TPU_SERVING_BATCH`` bounds the width), matching the
    sequential runs' released values config-for-config.
  * per-tenant budgets: :class:`~pipelinedp_tpu.budget_accounting
    .TenantBudgetLedger` + a per-tenant ReleaseJournal thread the
    existing spend-journal / at-most-once machinery through the session,
    so two tenants query one resident dataset without sharing budget.

L5 user code stays declarative: ``dataframes.QueryBuilder.on(session)``
builds queries against a session exactly like against a frame.

See SERVING.md for the session lifecycle, memory/eviction knobs, tenant
budget semantics and the interaction with checkpoint/resume.
"""

from pipelinedp_tpu.serving.session import (  # noqa: F401
    EVENT_BOUND_EVICTIONS, EVENT_BOUND_HITS, EVENT_BOUND_MISSES,
    EVENT_QUERIES, BATCH_WIDTH_ENV, RESIDENT_BYTES_ENV, DatasetSession,
    QueryConfig, SessionClosedError, StaleDatasetError, TenantState,
    batch_width, resident_byte_budget, serving_counters)
from pipelinedp_tpu.budget_accounting import (  # noqa: F401
    BudgetExhaustedError, TenantBudgetLedger)
