"""Resident-dataset query serving: pay encode + sort once, serve many
DP queries per launch.

The production shape for "millions of users" (ROADMAP north star) is not
one batch job but many DP queries per day against the same dataset. This
package is the long-lived serving layer over the columnar engine:

  * :class:`DatasetSession` runs the wire pipeline ONCE — the SlabDriver
    streams the dataset through encode / per-bucket radix sort /
    transfer in retain-wire mode — and keeps the sorted wire chunks as a
    reusable handle (device-resident when they fit the placement's byte
    budget, host slab cache otherwise; ``PIPELINEDP_TPU_RESIDENT_BYTES``).
    Every subsequent query is kernel + fused epilogue only, bit-identical
    to the same query run cold.
  * :meth:`DatasetSession.query_batch` packs concurrent queries that
    share the sorted wire but differ in metric set / epsilon / clip
    bounds into ONE vmapped launch per chunk
    (``PIPELINEDP_TPU_SERVING_BATCH`` bounds the width), matching the
    sequential runs' released values config-for-config.
  * per-tenant budgets: :class:`~pipelinedp_tpu.budget_accounting
    .TenantBudgetLedger` + a per-tenant ReleaseJournal thread the
    existing spend-journal / at-most-once machinery through the session,
    so two tenants query one resident dataset without sharing budget.

L5 user code stays declarative: ``dataframes.QueryBuilder.on(session)``
builds queries against a session exactly like against a frame.

Live (streaming-append) sessions extend the session with crash-
exactly-once ingest and continual releases (SERVING.md "Live
sessions"): :class:`~pipelinedp_tpu.serving.live.LiveDatasetSession`
accepts micro-batch appends committed through a fsync'd append WAL
(SIGKILL lands the reopened session at exactly epoch N or N+1;
duplicate batches are digest-idempotent), windows the epoch axis
(:class:`~pipelinedp_tpu.serving.live.WindowSpec` — tumbling/sliding,
watermark + late-arrival policy), and releases each sealed window
exactly once across restarts through a
:class:`~pipelinedp_tpu.serving.live.ReleaseSchedule`.

The durable fleet layer (SERVING.md "Fleet operation") sits on top:

  * :class:`~pipelinedp_tpu.serving.store.SessionStore` spills sessions
    to an atomic, per-chunk-digested on-disk layout;
    ``session.save(store)`` + ``store.open(name)`` survive process
    death with bit-identical warm queries, reattached per-tenant WAL
    journals/ledgers, and cross-restart release replays refused.
  * :class:`~pipelinedp_tpu.serving.manager.SessionManager` admits many
    sessions under one residency budget with an LRU demotion ladder
    (device → host slab → disk spill → on-demand re-hydration), a
    bounded in-flight admission gate (typed
    ``SessionOverloadedError`` shedding), and per-query deadlines
    (``QueryDeadlineError`` riding the DispatchWatchdog).
  * :mod:`~pipelinedp_tpu.serving.fleet` adds host-death failover
    (SERVING.md "Fleet failover"): fencing-token single-writer leases
    per stored session (stale ex-primaries are refused at the WAL),
    digest-verified hot followers serving warm read-only queries,
    exactly-once release catch-up across promotion, and a
    :class:`~pipelinedp_tpu.serving.fleet.FleetRouter` that routes by
    shard ownership, sheds across hosts, and hedges warm reads.

See SERVING.md for the session lifecycle, memory/eviction knobs, tenant
budget semantics and the interaction with checkpoint/resume.
"""

from pipelinedp_tpu.serving.session import (  # noqa: F401
    EVENT_BOUND_EVICTIONS, EVENT_BOUND_HITS, EVENT_BOUND_MISSES,
    EVENT_DEADLINE_HITS, EVENT_DEVICE_FALLBACKS, EVENT_PLANNER_CACHE_SKIPS,
    EVENT_PLANNER_DEDUPES, EVENT_PLANNER_GROUPS, EVENT_QUERIES,
    EVENT_REHYDRATIONS, BATCH_WIDTH_ENV, DEADLINE_ENV,
    EPILOGUE_WORKERS_ENV, RESIDENT_BYTES_ENV,
    DatasetSession, QueryConfig, SessionClosedError, SessionReadOnlyError,
    StaleDatasetError, TenantState, batch_width, default_deadline_s,
    epilogue_workers, resident_byte_budget, serving_counters)
from pipelinedp_tpu.serving.planner import (  # noqa: F401
    LaunchGroup, PlanEntry, QueryPlan, ReplayLane, compile_plan)
from pipelinedp_tpu.serving.store import (  # noqa: F401
    EVENT_BOUND_DROPPED, EVENT_OPENS, EVENT_SAVES, SESSION_DIR_ENV,
    SessionCorruptError, SessionNotFoundError, SessionStore,
    SessionStoreError)
from pipelinedp_tpu.serving.manager import (  # noqa: F401
    EVENT_DEMOTIONS, EVENT_SHED, EVENT_SPILLS, INFLIGHT_ENV,
    SessionManager, SessionOverloadedError, fleet_counters,
    max_inflight_default)
from pipelinedp_tpu.serving.live import (  # noqa: F401
    EVENT_APPENDS, EVENT_APPEND_DUPLICATES, EVENT_APPENDS_SHED,
    EVENT_EPOCH_FOLDS, EVENT_LATE_DEADLETTERED, EVENT_LATE_REJECTED,
    EVENT_RELEASES_RECOVERED, EVENT_RELEASES_SUPPRESSED,
    EVENT_SCHEDULED_RELEASES, APPEND_COMMIT_WINDOW_ENV, MAX_PENDING_ENV,
    AppendResult, IngestOverloadedError, LateArrivalError,
    LiveDatasetSession, ReleaseSchedule, WindowSpec,
    append_commit_window_s, live_counters,
    max_pending_appends_default, window_seed)
from pipelinedp_tpu.serving.fleet import (  # noqa: F401
    FOLLOWER_POLL_ENV, LEASE_TTL_ENV, FleetRouter, FollowerSession,
    LeaseHeldError, LeaseLostError, SessionLease, StaleWriterError,
    follower_poll_s, lease_ttl_s)
from pipelinedp_tpu.serving.fleet import (  # noqa: F401
    fleet_counters as failover_counters)
from pipelinedp_tpu.budget_accounting import (  # noqa: F401
    BudgetExhaustedError, TenantBudgetLedger)
from pipelinedp_tpu.runtime.watchdog import QueryDeadlineError  # noqa: F401
from pipelinedp_tpu.runtime.journal import DoubleReleaseError  # noqa: F401
from pipelinedp_tpu.obs.audit import (  # noqa: F401
    AuditCorruptError, AuditRecord, AuditTrail)
from pipelinedp_tpu.obs.ops_plane import (  # noqa: F401
    OPS_PORT_ENV, OpsServer, serve_ops)
