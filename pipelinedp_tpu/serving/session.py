"""DatasetSession: the resident-dataset serving layer (SERVING.md).

One session = one ingested dataset + any number of DP queries against
it. Ingest pays the dominant e2e phases (host encode, per-bucket radix
sort, and — for device-resident handles — the host->device transfer)
exactly once; queries replay the retained wire through the chunk
kernels, and repeat queries with identical bounding configuration skip
even the kernel via the session's accumulator ("bound") cache.

Exactness contract: a query answered from a session is BIT-IDENTICAL —
released values and kept partitions — to the same query run cold through
``JaxDPEngine(accountant, seed=s, stream_chunks=session.n_chunks, ...)``
on the source columns, on single-device and on a mesh. The bound cache
preserves this automatically: its key includes the kernel-key
fingerprint, so a hit replays exactly the accumulators that key would
have produced.

Thread-safety: ``query`` may be called concurrently from many threads.
Shared state (the bound cache, tenant ledgers and journals, the
epilogue cache, profiler counters) is lock-guarded; everything else
(engine, accountant, result) is per-query local. Two racing misses of
the same bound-cache key may both compute — they produce identical
arrays, so the race costs work, never correctness.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import combiners as combiners_lib
from pipelinedp_tpu import jax_engine
from pipelinedp_tpu import profiler
from pipelinedp_tpu.aggregate_params import (AggregateParams, MechanismType,
                                             Metric, Metrics, NoiseKind)
from pipelinedp_tpu.obs import audit as audit_lib
from pipelinedp_tpu.obs import flight as obs_flight
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.ops import columnar, encoding, finalize as finalize_ops
from pipelinedp_tpu.ops import streaming
from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib
from pipelinedp_tpu.runtime import journal as journal_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import watchdog as watchdog_lib

# Tuning knobs (validated via native.loader.env_int; README "Tuning
# knobs" + SERVING.md):
#   PIPELINEDP_TPU_RESIDENT_BYTES — per-session resident byte budget:
#     the wire slab goes device-resident when it fits, and the bound
#     cache LRU-evicts to stay under what remains (default 1 GiB).
#   PIPELINEDP_TPU_SERVING_BATCH — max query configs packed into one
#     vmapped launch by query_batch (default 32).
#   PIPELINEDP_TPU_QUERY_DEADLINE_S — default per-query deadline in
#     seconds (0 = none): an expired query surfaces as a typed,
#     retryable QueryDeadlineError instead of running (or hanging)
#     unboundedly.
#   PIPELINEDP_TPU_EPILOGUE_WORKERS — bounded executor width for the
#     pipelined per-config finalizes of query_batch (default 2; 0 runs
#     epilogues synchronously). Released bits are identical at every
#     width: the plan fixes commit order and per-config keys before any
#     epilogue runs.
RESIDENT_BYTES_ENV = "PIPELINEDP_TPU_RESIDENT_BYTES"
BATCH_WIDTH_ENV = "PIPELINEDP_TPU_SERVING_BATCH"
DEADLINE_ENV = "PIPELINEDP_TPU_QUERY_DEADLINE_S"
EPILOGUE_WORKERS_ENV = "PIPELINEDP_TPU_EPILOGUE_WORKERS"

# Profiler event counters (profiler.count_event / event_count; the
# replay-side counters live in ops/streaming.py, the fleet-level
# admission/demotion counters in serving/manager.py):
EVENT_QUERIES = "serving/queries"
EVENT_BOUND_HITS = "serving/bound_cache_hits"
EVENT_BOUND_MISSES = "serving/bound_cache_misses"
EVENT_BOUND_EVICTIONS = "serving/bound_cache_evictions"
# Graceful degradation: device-resident replays that hit
# RESOURCE_EXHAUSTED and fell back to host-window shipping instead of
# failing the query.
EVENT_DEVICE_FALLBACKS = "serving/device_fallbacks"
# Queries that tripped their per-query deadline (QueryDeadlineError).
EVENT_DEADLINE_HITS = "serving/query_deadline_hits"
# Spilled sessions re-hydrated from the store on demand.
EVENT_REHYDRATIONS = "serving/sessions_rehydrations"
# Slow-query capture bundles written (obs/flight.py; PR 13).
EVENT_SLOW_CAPTURES = "serving/slow_query_captures"
# Query-plane (serving/planner.py) counters: batch configs that skipped
# replay on a bound-cache hit, configs that deduped onto another
# config's replay lane, and fused launch groups compiled.
EVENT_PLANNER_CACHE_SKIPS = "serving/planner_cache_skips"
EVENT_PLANNER_DEDUPES = "serving/planner_dedupes"
EVENT_PLANNER_GROUPS = "serving/planner_fused_groups"

# Per-process query trace ids: "q<pid>-<n>". The same id lands on the
# query's root span (attr "qid"), its flight-recorder events, its audit
# record (trace_id) and any slow-query capture file — the correlation
# key of the operational plane. Never derived from data.
_QUERY_IDS = itertools.count()


def _next_query_id() -> str:
    return f"q{os.getpid()}-{next(_QUERY_IDS)}"


def resident_byte_budget() -> int:
    """Validated PIPELINEDP_TPU_RESIDENT_BYTES (default 1 GiB)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(RESIDENT_BYTES_ENV, 1 << 30, 1 << 20, 1 << 40)


def batch_width() -> int:
    """Validated PIPELINEDP_TPU_SERVING_BATCH (default 32): the max
    configs one vmapped launch carries; wider batches split."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(BATCH_WIDTH_ENV, 32, 1, 1024)


def epilogue_workers() -> int:
    """Validated PIPELINEDP_TPU_EPILOGUE_WORKERS (default 2): executor
    width for query_batch's pipelined per-config finalizes; 0 disables
    the overlap (epilogues run synchronously after their group)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(EPILOGUE_WORKERS_ENV, 2, 0, 32)


def default_deadline_s() -> Optional[float]:
    """Validated PIPELINEDP_TPU_QUERY_DEADLINE_S (None when 0/unset)."""
    from pipelinedp_tpu.native import loader
    seconds = loader.env_int(DEADLINE_ENV, 0, 0, 24 * 3600)
    return float(seconds) if seconds > 0 else None


def serving_counters() -> Dict[str, int]:
    """Snapshot of the serving counters (bench.py surfaces this; the
    fleet-level admission/demotion counters ride
    serving.fleet_counters())."""
    return {
        "queries": profiler.event_count(EVENT_QUERIES),
        "bound_cache_hits": profiler.event_count(EVENT_BOUND_HITS),
        "bound_cache_misses": profiler.event_count(EVENT_BOUND_MISSES),
        "bound_cache_evictions": profiler.event_count(
            EVENT_BOUND_EVICTIONS),
        "wire_replays": profiler.event_count(
            streaming.EVENT_SERVING_REPLAYS),
        "kernel_dispatches": profiler.event_count(
            streaming.EVENT_SERVING_LAUNCHES),
        "device_fallbacks": profiler.event_count(EVENT_DEVICE_FALLBACKS),
        "query_deadline_hits": profiler.event_count(EVENT_DEADLINE_HITS),
        "slow_query_captures": profiler.event_count(EVENT_SLOW_CAPTURES),
        "planner_cache_skips": profiler.event_count(
            EVENT_PLANNER_CACHE_SKIPS),
        "planner_dedupes": profiler.event_count(EVENT_PLANNER_DEDUPES),
        "planner_fused_groups": profiler.event_count(EVENT_PLANNER_GROUPS),
    }


class StaleDatasetError(RuntimeError):
    """The source columns were mutated after ingest: the retained wire no
    longer describes the data the caller is looking at, so the session
    refuses to answer (re-ingest to serve the new data)."""


class SessionClosedError(RuntimeError):
    """The session was closed; its handle and caches are gone."""


class SessionReadOnlyError(RuntimeError):
    """A mutating operation on a ``read_only=True`` (follower) session:
    followers hold no single-writer lease, so append/save/tenant/spill
    paths refuse — promote to primary first (serving/fleet.py)."""


@dataclasses.dataclass
class TenantState:
    """One tenant's serving-side state: the cross-query budget ledger and
    the at-most-once release journal. Tenants never share either — one
    tenant replaying a release or exhausting its epsilon cannot touch
    another tenant's ledger or journal."""
    ledger: budget_accounting.TenantBudgetLedger
    release_journal: journal_lib.ReleaseJournal


@dataclasses.dataclass
class QueryConfig:
    """One query of a batched launch (DatasetSession.query_batch).

    Configs in one batch share the session's sorted wire and pack into a
    single vmapped kernel launch per chunk; metrics / epsilon / clip
    bounds / caps / seed / tenant vary per config.
    """
    metrics: List[Metric]
    epsilon: float
    delta: float = 0.0
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    max_partitions_contributed: Optional[int] = None
    max_contributions_per_partition: Optional[int] = None
    max_contributions: Optional[int] = None
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    min_sum_per_partition: Optional[float] = None
    max_sum_per_partition: Optional[float] = None
    seed: int = 0
    tenant: Optional[str] = None

    def to_params(self) -> AggregateParams:
        return AggregateParams(
            metrics=list(self.metrics),
            noise_kind=self.noise_kind,
            max_partitions_contributed=self.max_partitions_contributed,
            max_contributions_per_partition=self.
            max_contributions_per_partition,
            max_contributions=self.max_contributions,
            min_value=self.min_value,
            max_value=self.max_value,
            min_sum_per_partition=self.min_sum_per_partition,
            max_sum_per_partition=self.max_sum_per_partition)


@dataclasses.dataclass
class _BoundCacheEntry:
    result: Any  # accs, or (accs, qhist)
    nbytes: int


@dataclasses.dataclass
class _PreparedQuery:
    """One config's engine-side state, prepared before the batched
    accumulate (budget requests registered, keys drawn, caps derived)."""
    index: int
    engine: Any
    accountant: Any
    compound: Any
    sel_spec: Any
    params: AggregateParams
    k_kernel: Any
    k_select: Any
    k_noise: Any
    key_counter: int
    linf_cap: int
    l0_cap: int
    l1_cap: Optional[int]
    row_lo: float
    row_hi: float
    glo: float
    ghi: float
    middle: float
    need_flags: tuple
    has_group_clip: bool
    # Tenant bookkeeping for exact refunds on a failed batch: the
    # pre-run ledger charge and the TenantState it was charged against
    # (None for non-tenant configs).
    state: Any = None
    charge: Any = None
    # Query-plane routing (serving/planner.py): the config's resolved
    # bound-cache key, and the wall-clock duration of ITS replay +
    # finalize (set when its epilogue completes; audit falls back to
    # the batch duration when the config never finished).
    bound_key: Any = None
    duration_s: Optional[float] = None


class DatasetSession:
    """A resident dataset serving many DP queries (module docstring).

    data: ColumnarData or EncodedColumns (use :meth:`from_frame` for
      pandas / dict frames).
    public_partitions: fixed at ingest — the public filter and the
      partition vocabulary shape the wire, so every query of the session
      shares them.
    mesh: a ``parallel.sharded.make_mesh`` mesh; the wire is ingested in
      the mesh's bucket layout and queries replay sharded. Device
      residency (skipping per-query transfer) is single-device only.
    n_chunks: wire chunk count; defaults to the streaming path's own
      choice for this row count, so cold-parity engines need
      ``stream_chunks=session.n_chunks``.
    resident_bytes: overrides PIPELINEDP_TPU_RESIDENT_BYTES.
    verify_source: keep a reference to the source columns and refuse
      queries (StaleDatasetError) if their digest no longer matches the
      ingest-time fingerprint. Costs one O(n) column-sum per query.
    """

    # Duck-typed marker JaxDPEngine.aggregate dispatches on (keeps the
    # engine free of serving imports).
    is_resident_dataset = True

    def __init__(self,
                 data,
                 *,
                 public_partitions: Optional[Sequence[Any]] = None,
                 mesh=None,
                 n_chunks: Optional[int] = None,
                 resident_bytes: Optional[int] = None,
                 value_transfer_dtype=None,
                 secure_host_noise: bool = True,
                 segment_sort="auto",
                 compact_merge="auto",
                 epilogue_cache: Optional[
                     finalize_ops.EpilogueCache] = None,
                 verify_source: bool = True,
                 name: str = "dataset"):
        self._init_common(name=name, mesh=mesh,
                          public_partitions=public_partitions,
                          secure_host_noise=secure_host_noise,
                          segment_sort=segment_sort,
                          compact_merge=compact_merge,
                          epilogue_cache=epilogue_cache,
                          resident_bytes=resident_bytes)

        with profiler.stage("dp/ingest"), \
                obs_trace.span("serving/ingest", session=name):
            pid, pk, value, _, pk_vocab = encoding.encode_rows(
                data, True, None, None,
                public_partitions=self._public, factorize_pid=False)
            self._pk_vocab = pk_vocab
            n_dev = mesh.devices.size if mesh is not None else 1
            self._wire = streaming.ingest_resident_wire(
                pid, pk, value,
                num_partitions=max(len(pk_vocab), 1),
                n_chunks=n_chunks, n_dev=n_dev,
                value_transfer_dtype=value_transfer_dtype)
        if verify_source:
            self._source = data
            self._source_digest = checkpoint_lib.array_digest(
                np.asarray(data.pid), np.asarray(data.pk),
                None if data.value is None else np.asarray(data.value))
        else:
            self._source = self._source_digest = None
        # Device residency: the sorted wire moves onto the device when it
        # fits the byte budget, so warm queries skip the host->device
        # transfer too. Mesh handles stay host-side (each chunk ships
        # sharded per query).
        if (mesh is None and self._wire.n_rows > 0
                and self._wire.host_nbytes <= self._byte_budget):
            self._wire.ensure_device()

    def _init_common(self, *, name, mesh, public_partitions,
                     secure_host_noise, segment_sort, compact_merge,
                     epilogue_cache, resident_bytes) -> None:
        """State shared by ingest (__init__) and store re-hydration
        (:meth:`_restore`)."""
        self._name = name
        self._mesh = mesh
        self._public = (list(public_partitions)
                        if public_partitions is not None else None)
        self._secure_host_noise = secure_host_noise
        self._segment_sort = segment_sort
        self._compact_merge = compact_merge
        self._epilogue_cache = (epilogue_cache if epilogue_cache is not None
                                else finalize_ops.default_cache())
        self._byte_budget = (int(resident_bytes) if resident_bytes is not None
                             else resident_byte_budget())
        self._closed = False
        self._lock = threading.Lock()
        self._bound_cache: "collections.OrderedDict[tuple, _BoundCacheEntry]"
        self._bound_cache = collections.OrderedDict()
        self._cache_bytes = 0
        self._tenants: Dict[str, TenantState] = {}
        self._queries = 0
        # Query-plane accounting (serving/planner.py): cumulative plan
        # stats + replay/epilogue wall time for the overlap ratio.
        self._planner_totals = {
            "batches": 0, "configs": 0, "cache_skips": 0, "dedupes": 0,
            "lanes": 0, "fused_groups": 0, "replay_s": 0.0,
            "epilogue_s": 0.0, "wall_s": 0.0}
        self._frame_meta = None  # set by from_frame
        # Durable-fleet state (serving/store.py, serving/manager.py):
        #   _store_binding — (SessionStore, name) after save()/open();
        #   _manager — the SessionManager this session is admitted to;
        #   _spilled — wire bytes live only in the store (rung 3 of the
        #     demotion ladder); queries re-hydrate on demand;
        #   _active — queries currently executing (spill never unloads a
        #     handle a replay is reading);
        #   _lifecycle_lock — serializes spill / re-hydrate / query
        #     start+finish, so lifecycle transitions and replays never
        #     interleave;
        #   _deadline_tls — the running query's Deadline, read by
        #     _accumulate on whatever thread executes the replay.
        self._store_binding = None
        self._manager = None
        # Fleet tier (serving/fleet.py):
        #   _lease — the SessionLease a writable store-bound open holds
        #     (its admit() fences every WAL append on live sessions);
        #   _read_only — a follower replica: every mutating path
        #     refuses with SessionReadOnlyError.
        self._lease = None
        self._read_only = False
        self._spilled = False
        self._active = 0
        self._lifecycle_lock = threading.Lock()
        self._deadline_tls = threading.local()
        # Release audit trail (obs/audit.py): in-memory until the
        # session is store-bound, then durable under the store
        # (_bind_audit) so outcomes survive process death.
        self._audit = audit_lib.AuditTrail()

    @classmethod
    def _restore(cls, wire: streaming.ResidentWire,
                 pk_vocab: encoding.Vocabulary, *,
                 public_partitions, mesh, name: str,
                 secure_host_noise: bool, segment_sort, compact_merge,
                 resident_bytes: Optional[int],
                 epilogue_cache: Optional[finalize_ops.EpilogueCache],
                 store_binding) -> "DatasetSession":
        """A session over an already-validated wire handle — the store's
        re-hydration path (serving/store.py). No ingest runs, no source
        columns exist (``verify_source`` has nothing to verify: the wire
        was digest-validated against its fingerprint on load)."""
        self = cls.__new__(cls)
        self._init_common(name=name, mesh=mesh,
                          public_partitions=public_partitions,
                          secure_host_noise=secure_host_noise,
                          segment_sort=segment_sort,
                          compact_merge=compact_merge,
                          epilogue_cache=epilogue_cache,
                          resident_bytes=resident_bytes)
        self._pk_vocab = pk_vocab
        self._wire = wire
        self._source = self._source_digest = None
        self._store_binding = store_binding
        self._bind_audit()
        if (mesh is None and wire.n_rows > 0 and wire.loaded
                and wire.host_nbytes <= self._byte_budget):
            wire.ensure_device()
        return self

    # -- construction from L5 frames ------------------------------------

    @classmethod
    def from_frame(cls, df, privacy_unit_column: str, partition_key,
                   value_column: Optional[str] = None, *,
                   public_keys: Optional[Sequence[Any]] = None,
                   **session_kwargs) -> "DatasetSession":
        """Ingests a pandas DataFrame or dict-of-arrays frame, fixing the
        (privacy unit, partition key, value) columns for the session's
        lifetime. ``QueryBuilder.on(session)`` then builds declarative
        queries against it (dataframes.py)."""
        from pipelinedp_tpu import dataframes

        converter = dataframes._create_converter(df)
        names = converter.column_names(df)
        for col in ([privacy_unit_column] + dataframes._as_list(
                partition_key) + ([value_column] if value_column else [])):
            if col not in names:
                raise ValueError(f"Column {col} is not present in the frame")
        columns = dataframes.Columns(privacy_unit_column, partition_key,
                                     value_column)
        data = converter.frame_to_columns(df, columns)
        session = cls(data, public_partitions=public_keys,
                      **session_kwargs)
        session._frame_meta = {
            "converter": converter,
            "column_names": list(names),
            "privacy_unit_column": privacy_unit_column,
            "partition_key": dataframes._as_list(partition_key),
            "value_column": value_column,
        }
        return session

    # -- introspection ---------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def pk_vocab(self) -> encoding.Vocabulary:
        return self._pk_vocab

    @property
    def n_rows(self) -> int:
        return self._wire.n_rows

    @property
    def num_partitions(self) -> int:
        return self._wire.num_partitions

    @property
    def n_chunks(self) -> int:
        """Chunk count of the retained wire — the ``stream_chunks=`` a
        cold engine needs for bit-parity with this session."""
        return self._wire.n_chunks

    @property
    def fingerprint(self) -> str:
        """Wire-handle identity (wirecodec.resident_fingerprint)."""
        return self._wire.fingerprint

    @property
    def mesh(self):
        return self._mesh

    @property
    def public_partitions(self):
        return list(self._public) if self._public is not None else None

    @property
    def frame_meta(self) -> Optional[dict]:
        """Frame binding of a from_frame session (None otherwise)."""
        return self._frame_meta

    def stats(self) -> dict:
        """Resident-memory and cache accounting of this session."""
        with self._lock:
            return {
                "wire_host_bytes": self._wire.host_nbytes,
                "wire_device_bytes": self._wire.device_nbytes,
                "bound_cache_bytes": self._cache_bytes,
                "bound_cache_entries": len(self._bound_cache),
                "resident_bytes": (self._wire.host_nbytes
                                   + self._wire.device_nbytes
                                   + self._cache_bytes),
                "byte_budget": self._byte_budget,
                "queries": self._queries,
                "n_chunks": self._wire.n_chunks,
                "spilled": self._spilled,
                "active_queries": self._active,
                "store": (self._store_binding[0].path(self._store_binding[1])
                          if self._store_binding is not None else None),
                "read_only": self._read_only,
                "fleet": ({"lease": self._lease.status()}
                          if self._lease is not None else None),
                "planner": self._planner_stats_locked(),
                "tenants": {
                    tid: {
                        "total_epsilon": st.ledger.total_epsilon,
                        "spent_epsilon": st.ledger.spent_epsilon,
                        "remaining_epsilon": st.ledger.remaining_epsilon,
                        "total_delta": st.ledger.total_delta,
                        "spent_delta": st.ledger.spent_delta,
                        "releases": len(st.release_journal),
                    }
                    for tid, st in self._tenants.items()
                },
            }

    def _planner_stats_locked(self) -> dict:
        """The query-plane sub-dict of stats() (caller holds _lock).

        epilogue_overlap_ratio estimates how much per-config finalize
        time was hidden behind batched replays: with replay + epilogue
        busy time R and E inside total batch wall W, anything past W
        must have run concurrently, so overlap = clamp((R + E - W) / E).
        0.0 = fully sequential, 1.0 = every epilogue hidden."""
        t = self._planner_totals
        overlap = 0.0
        if t["epilogue_s"] > 0.0:
            overlap = (t["replay_s"] + t["epilogue_s"] - t["wall_s"]
                       ) / t["epilogue_s"]
            overlap = max(0.0, min(1.0, overlap))
        return {
            "batches": t["batches"],
            "configs": t["configs"],
            "cache_skips": t["cache_skips"],
            "dedupes": t["dedupes"],
            "lanes": t["lanes"],
            "fused_groups": t["fused_groups"],
            "epilogue_overlap_ratio": round(overlap, 4),
        }

    def close(self) -> None:
        """Frees the handle (device + host) and every cache; further
        queries raise SessionClosedError. A held single-writer lease is
        released (marked, not deleted — the next acquire takes over
        immediately instead of waiting out the TTL)."""
        with self._lock:
            self._closed = True
            self._wire.drop_device()
            self._bound_cache.clear()
            self._cache_bytes = 0
            self._source = None
        if self._lease is not None:
            try:
                self._lease.release()
            except OSError:
                pass  # best effort: expiry reclaims it anyway
        self._audit.close()

    def __enter__(self) -> "DatasetSession":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()

    # -- persistence & fleet lifecycle (serving/store.py, manager.py) ----

    @property
    def is_spilled(self) -> bool:
        """True when the wire bytes live only in the session store (the
        demotion ladder's disk rung); the next query re-hydrates."""
        return self._spilled

    @property
    def store_binding(self):
        """(SessionStore, stored name) after save()/open(), else None."""
        return self._store_binding

    @property
    def audit_trail(self) -> audit_lib.AuditTrail:
        """The session's release audit trail (obs/audit.py): one record
        per finished query with mechanism kinds, (ε, δ), kept/dropped
        partition counts, timing and a typed outcome. Durable (WAL
        under the store) once the session is store-bound."""
        return self._audit

    @property
    def read_only(self) -> bool:
        """True for a follower replica (serving/fleet.py): no lease, no
        WAL handles; every mutating path refuses."""
        return self._read_only

    @property
    def lease(self):
        """The held SessionLease of a writable store-bound open (None
        for leaseless or read-only sessions)."""
        return self._lease

    def _ensure_writable(self, what: str) -> None:
        if self._read_only:
            raise SessionReadOnlyError(
                f"session {self._name!r} is a read-only follower "
                f"replica; {what} needs the single-writer lease — "
                f"promote first (serving/fleet.py)")

    def _wal_fence(self):
        """The fence callable for this session's WALs (None when no
        lease is held — leaseless legacy opens stay unfenced)."""
        return self._lease.admit if self._lease is not None else None

    def _attach_lease(self, lease) -> None:
        """Binds an acquired SessionLease; live sessions additionally
        fence their WALs (the override in serving/live.py)."""
        self._lease = lease

    def _bind_audit(self) -> None:
        """Moves the audit trail onto its durable WAL under the bound
        store (idempotent; the in-memory prefix is replayed onto disk),
        and binds the process flight recorder's spool/dump next to the
        WALs — so a SIGKILL'd or wedged process leaves its post-mortem
        where its durable state lives (obs/flight.py)."""
        if self._store_binding is None:
            return
        store, name = self._store_binding
        obs_flight.ensure_process_spool(store.flight_dir())
        if self._audit.durable:
            return
        self._audit.bind(store.audit_path(name))

    def save(self, store=None) -> str:
        """Spills the session durably: wire chunks (per-chunk digested),
        bound-cache entries (content-digested), tenant registrations —
        and migrates every tenant's release journal and budget ledger
        onto fsync'd WALs under the store, so ``SessionStore.open``
        after process death re-hydrates a session whose warm queries are
        bit-identical and whose cross-restart release/spend replays are
        still refused. Returns the on-disk session path. The session
        stays fully usable (saving is not spilling)."""
        if store is None:
            if self._store_binding is None:
                raise ValueError(
                    "session has no bound store; pass save(store=)")
            store = self._store_binding[0]
        self._check_open()
        self._ensure_writable("save()")
        with obs_trace.span("fleet/save", session=self._name):
            path = store.save(self)
        self._bind_audit()
        return path

    def spill(self, store=None) -> bool:
        """Demotes the session to the disk rung: saves (if needed) and
        frees the wire bytes (host and device) and the in-memory bound
        cache. Returns False — and keeps everything — when a query is
        executing (a replay must never lose the slab under its feet).
        The persisted bound entries re-hydrate with the wire."""
        if self._read_only:
            return False  # followers keep their replica resident
        with self._lifecycle_lock:
            if self._active > 0:
                return False
            if self._spilled:
                return True
            self.save(store)
            with self._lock:
                self._wire.unload()
                self._bound_cache.clear()
                self._cache_bytes = 0
                self._spilled = True
            return True

    def rehydrate(self) -> None:
        """Loads the wire bytes (and persisted bound entries) back from
        the bound store; idempotent. Chunk digests are validated against
        the handle's fingerprint — a corrupted spill refuses
        (SessionCorruptError) rather than serving wrong bits; corrupted
        bound entries are dropped and recompute via kernel replay."""
        with self._lifecycle_lock:
            self._rehydrate_locked()

    def _rehydrate_locked(self) -> None:
        if not self._spilled:
            return
        t0 = time.perf_counter()
        with obs_trace.span("fleet/rehydrate", session=self._name):
            store, name = self._store_binding
            slab, bound_entries = store.load_payload(name)
            with self._lock:
                self._check_open()
                self._wire.reload(slab)
                self._spilled = False
            profiler.count_event(EVENT_REHYDRATIONS)
            if (self._mesh is None and self._wire.n_rows > 0
                    and self._wire.host_nbytes <= self._byte_budget):
                self._wire.ensure_device()
            for key, result in bound_entries:
                self._cache_insert(key, result)
        obs_metrics.rehydration_seconds().observe(
            time.perf_counter() - t0)

    def demote_device(self) -> bool:
        """Demotion rung 1: frees the device copy of the wire (the host
        slab stays authoritative; queries re-ship windows)."""
        with self._lock:
            if not self._wire.device_resident:
                return False
            self._wire.drop_device()
            return True

    @contextlib.contextmanager
    def _pinned(self):
        """Query-lifetime pin: re-hydrates a spilled session, then holds
        ``_active`` > 0 so a concurrent spill can never unload the slab
        a replay is reading. The manager is notified *after* the
        lifecycle lock drops (its budget enforcement takes other
        sessions' lifecycle locks — never while we hold ours)."""
        with self._lifecycle_lock:
            was_spilled = self._spilled
            if was_spilled:
                self._rehydrate_locked()
            with self._lock:
                self._active += 1
        try:
            if self._manager is not None:
                self._manager.notify_used(self, rehydrated=was_spilled)
            yield
        finally:
            with self._lock:
                self._active -= 1

    # -- integrity -------------------------------------------------------

    def verify_source(self) -> None:
        """Refuses a mutated source dataset: recomputes the source-column
        digest and compares it to the ingest-time fingerprint (the same
        evidence checkpoint resume uses to refuse mutated inputs)."""
        if self._source is None:
            return
        digest = checkpoint_lib.array_digest(
            np.asarray(self._source.pid), np.asarray(self._source.pk),
            None if self._source.value is None else np.asarray(
                self._source.value))
        if digest != self._source_digest:
            raise StaleDatasetError(
                f"session {self._name!r}: the source columns changed "
                f"after ingest (digest {digest} != ingest "
                f"{self._source_digest}); the retained wire no longer "
                f"describes this data — re-ingest to serve it")

    def _check_open(self) -> None:
        if self._closed:
            raise SessionClosedError(
                f"session {self._name!r} is closed")

    def _check_engine_compat(self, engine, public_partitions) -> None:
        """Engine-side gate (called from JaxDPEngine._aggregate)."""
        self._check_open()
        if engine._mesh is not self._mesh:
            raise ValueError(
                "engine mesh does not match the session's ingest mesh; "
                "a resident wire replays only on the topology it was "
                "ingested for")
        pub = (list(public_partitions)
               if public_partitions is not None else None)
        if pub != self._public:
            raise ValueError(
                "public_partitions differ from the session's: the public "
                "filter and partition vocabulary are fixed at ingest")
        self.verify_source()

    # -- tenants ---------------------------------------------------------

    def register_tenant(self, tenant_id: str, total_epsilon: float,
                        total_delta: float = 0.0,
                        release_journal: Optional[
                            journal_lib.ReleaseJournal] = None,
                        window_epsilon: Optional[float] = None,
                        window_delta: Optional[float] = None
                        ) -> TenantState:
        """Creates a tenant with its own cross-query budget ledger and
        at-most-once release journal (a FileReleaseJournal makes the
        tenant's release history survive process death).

        On a store-bound session (after save()/open()) both are durable
        by default: the release journal and the ledger land on fsync'd
        WALs under the store, and the registration is recorded in the
        session manifest immediately — so a crash right after
        registration still reattaches the tenant on reopen.

        ``window_epsilon``/``window_delta`` cap the spend attributable to
        any single release window on a live session (charges tagged with
        a window label by the continual-release scheduler); untagged
        queries see only the total caps."""
        self._ensure_writable("register_tenant()")
        with self._lock:
            self._check_open()
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} already registered")
            wal = None
            if self._store_binding is not None:
                store, name = self._store_binding
                if release_journal is None:
                    release_journal = journal_lib.FileReleaseJournal(
                        store.tenant_release_path(name, tenant_id))
                wal = journal_lib.FileReleaseJournal(
                    store.tenant_ledger_path(name, tenant_id))
            state = TenantState(
                ledger=budget_accounting.TenantBudgetLedger(
                    tenant_id, total_epsilon, total_delta, wal=wal,
                    window_epsilon=window_epsilon,
                    window_delta=window_delta),
                release_journal=(release_journal if release_journal
                                 is not None else
                                 journal_lib.ReleaseJournal()))
            self._tenants[tenant_id] = state
        if self._store_binding is not None:
            store, name = self._store_binding
            store.record_tenant(name, tenant_id, total_epsilon, total_delta,
                                release_journal,
                                window_epsilon=window_epsilon,
                                window_delta=window_delta)
        return state

    def tenant(self, tenant_id: str) -> TenantState:
        with self._lock:
            if tenant_id not in self._tenants:
                raise ValueError(
                    f"tenant {tenant_id!r} is not registered; call "
                    f"register_tenant first")
            return self._tenants[tenant_id]

    # -- the bound (accumulator) cache -----------------------------------

    @staticmethod
    def _canonical(v):
        if isinstance(v, (tuple, list)):
            return tuple(DatasetSession._canonical(x) for x in v)
        if isinstance(v, np.generic):
            return v.item()
        return v

    def _cache_key(self, key_fp: str, kw: dict) -> tuple:
        return (key_fp,) + tuple(
            (k, self._canonical(kw[k])) for k in sorted(kw))

    def _resolved_sampler(self, mesh, kw: dict, wire=None) -> str:
        """The RESOLVED sampler this query config compiles against
        (streaming.resolved_sampler_desc), cached under the bound-cache
        key so flipping ``segment_sort`` between queries — e.g. two
        user-built engines over one session, or "auto" resolving
        differently for different caps — can never alias a cached
        accumulator produced by a different group stage."""
        wire = self._wire if wire is None else wire
        num_partitions = wire.num_partitions
        if mesh is not None:
            from pipelinedp_tpu.parallel import sharded
            num_partitions = sharded.padded_num_partitions(
                mesh, num_partitions)
        return streaming.resolved_sampler_desc(
            wire.fmt, kw.get("segment_sort", "auto"),
            wire.max_run, num_partitions=num_partitions,
            row_clip_lo=kw.get("row_clip_lo", -np.inf),
            row_clip_hi=kw.get("row_clip_hi", np.inf),
            linf_cap=kw.get("linf_cap", 1),
            l1_mode=kw.get("l1_cap") is not None,
            group_clip_lo=kw.get("group_clip_lo", -np.inf),
            group_clip_hi=kw.get("group_clip_hi", np.inf),
            need_flags=kw.get("need_flags", (True, True, True, True)))

    @staticmethod
    def _result_nbytes(result) -> int:
        arrays = []
        if isinstance(result, tuple) and not hasattr(result, "_fields"):
            accs, qhist = result
            arrays.extend(accs)
            if qhist is not None:
                arrays.append(qhist)
        else:
            arrays.extend(result)
        return int(sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in arrays))

    def _accumulate(self, k_kernel, *, mesh, resilience=None, **kw):
        """Accumulators for one query config — from the bound cache when
        this exact (kernel key, caps, clips, flags) was computed before
        (a hit is bitwise-exact by construction: the key includes the
        kernel-key fingerprint), replaying the retained wire otherwise.
        Called by JaxDPEngine._execute on the resident path."""
        return self._accumulate_wire(self._wire, None, k_kernel,
                                     mesh=mesh, resilience=resilience,
                                     **kw)

    def _accumulate_wire(self, wire, key_prefix, k_kernel, *, mesh,
                         resilience=None, **kw):
        """The replay-or-cache body of :meth:`_accumulate`, parameterized
        by the wire so live sessions can route window views through the
        same machinery. ``key_prefix`` (a tuple or None) is prepended to
        the bound-cache key — live sessions tag entries with the wire
        fingerprint so an epoch bump invalidates only the entries the
        fold actually changed.

        A running query's Deadline (thread-local, set by :meth:`query`)
        is injected into the replay's resilience bundle so the slab
        driver checks it cooperatively between windows. A
        device-resident replay that hits RESOURCE_EXHAUSTED degrades
        gracefully: the device copy is dropped and the replay re-issues
        with host-window shipping — same chunk kernels, same keys, same
        released bits, one fallback counter richer."""
        key_fp = checkpoint_lib.key_fingerprint(k_kernel)
        # The sampler enters the key as its RESOLVED identity, not the
        # raw knob string: knobs that compile the same kernel share the
        # entry ("auto" vs "hash" under the gate), knobs that compile
        # different group stages can never alias.
        kw_for_key = {k: v for k, v in kw.items() if k != "segment_sort"}
        cache_key = self._cache_key(key_fp, kw_for_key) + (
            ("resolved_sampler", self._resolved_sampler(mesh, kw, wire)),)
        if key_prefix is not None:
            cache_key = (key_prefix,) + cache_key
        with self._pinned():
            with self._lock:
                self._check_open()
                entry = self._bound_cache.get(cache_key)
                if entry is not None:
                    self._bound_cache.move_to_end(cache_key)
                    profiler.count_event(EVENT_BOUND_HITS)
                    obs_trace.event("bound_cache_hit")
                    return entry.result
            profiler.count_event(EVENT_BOUND_MISSES)
            deadline = getattr(self._deadline_tls, "value", None)
            if deadline is not None:
                if resilience is None:
                    from pipelinedp_tpu import runtime as runtime_lib
                    resilience = runtime_lib.StreamResilience()
                resilience.deadline = deadline
            t_replay0 = time.perf_counter()
            with obs_trace.span("serving/replay", session=self._name,
                                n_chunks=wire.n_chunks):
                try:
                    result = self._replay(k_kernel, mesh, resilience, kw,
                                          wire)
                except Exception as exc:
                    if (retry_lib.classify(exc) != retry_lib.OOM
                            or not wire.device_resident):
                        raise
                    # Graceful degradation: a device-resident replay that
                    # exhausted device memory falls back to shipping host
                    # windows instead of failing the query.
                    wire.drop_device()
                    profiler.count_event(EVENT_DEVICE_FALLBACKS)
                    obs_trace.event("device_fallback")
                    result = self._replay(k_kernel, mesh, resilience, kw,
                                          wire)
            obs_metrics.replay_seconds().observe(
                time.perf_counter() - t_replay0)
            self._cache_insert(cache_key, result)
            return result

    def _replay(self, k_kernel, mesh, resilience, kw, wire=None):
        wire = self._wire if wire is None else wire
        if mesh is not None:
            from pipelinedp_tpu.parallel import sharded
            mesh_kw = dict(kw)
            if mesh_kw.pop("quantile_spec", None) is not None:
                raise NotImplementedError(
                    "quantile replay is single-device only")
            return sharded.replay_resident_wire(
                mesh, k_kernel, wire, resilience=resilience,
                **mesh_kw)
        return streaming.replay_resident_wire(
            k_kernel, wire, resilience=resilience, **kw)

    def _cache_insert(self, cache_key: tuple, result) -> None:
        nbytes = self._result_nbytes(result)
        with self._lock:
            if self._closed or cache_key in self._bound_cache:
                return
            room = self._byte_budget - self._wire.device_nbytes
            if nbytes > room:
                return  # never evict the whole cache for one giant entry
            while self._cache_bytes + nbytes > room and self._bound_cache:
                _, evicted = self._bound_cache.popitem(last=False)
                self._cache_bytes -= evicted.nbytes
                profiler.count_event(EVENT_BOUND_EVICTIONS)
            self._bound_cache[cache_key] = _BoundCacheEntry(
                result=result, nbytes=nbytes)
            self._cache_bytes += nbytes

    # -- queries ---------------------------------------------------------

    def query(self,
              params: AggregateParams,
              *,
              epsilon: Optional[float] = None,
              delta: float = 0.0,
              seed: int = 0,
              tenant: Optional[str] = None,
              accountant: Optional[
                  budget_accounting.BudgetAccountant] = None,
              secure_host_noise: Optional[bool] = None,
              release_journal: Optional[
                  journal_lib.ReleaseJournal] = None,
              deadline_s: Optional[float] = None,
              fault_injector=None,
              watchdog_timeout_s: Optional[float] = None,
              retry_policy=None,
              trace_path: Optional[str] = None,
              out_explain_computation_report=None,
              _live=None
              ) -> jax_engine.LazyJaxResult:
        """Answers one DP query from the resident dataset.

        Budget comes from ``tenant=`` (charged against the tenant's
        ledger; releases go through the tenant's at-most-once journal),
        an explicit ``accountant=``, or a fresh NaiveBudgetAccountant
        over (epsilon, delta). The result is fully materialized before
        returning: failures surface HERE, so a tenant charge whose
        release token never committed is exactly refunded (the ledger,
        bound cache and journal are left as if the query never ran).

        ``deadline_s`` (default: the manager's deadline, else
        PIPELINEDP_TPU_QUERY_DEADLINE_S) bounds the query end to end:
        the slab driver checks the deadline between windows, and the
        whole replay+finalize runs under a DispatchWatchdog with the
        remaining budget — so even a *wedged* replay surfaces as a
        typed, retryable ``QueryDeadlineError`` within the deadline. A
        timed-out attempt is abandoned, not interrupted: its charge is
        conservatively kept (the abandoned worker could still commit a
        release), which is the same "err toward spent, never toward
        double-release" stance the at-most-once journal takes.

        ``fault_injector`` / ``watchdog_timeout_s`` / ``retry_policy``
        thread straight into the replay's slab driver (the same
        resilience surface a cold streamed run has — chaos and
        kill-harness coverage extends to serving through them).

        Operational plane (PR 13): every query gets a process-unique
        trace id ("q<pid>-<n>") that lands on its root span, its
        flight-recorder events, and its audit record — and when the
        query exceeds ``PIPELINEDP_TPU_SLOW_QUERY_S`` or lands within
        20% of its deadline (either outcome), a capture bundle (Chrome
        trace when tracing is on, metrics delta, flight-recorder
        slice) is written into the bounded
        ``PIPELINEDP_TPU_CAPTURE_DIR``, named by that trace id.

        Observability (OBSERVABILITY.md): the query runs under a
        ``serving/query`` root span (admission → replay → finalize
        children), lands one latency observation in the
        ``pipelinedp_tpu_query_seconds`` histogram, and appends one
        typed-outcome record to the session's audit trail — all
        regardless of success. ``trace_path`` writes THIS query's span
        tree as Chrome trace JSON when a tracer is installed
        (``obs.trace.install()`` / ``PIPELINEDP_TPU_TRACE``); it is a
        no-op otherwise. None of this can change released bits: spans
        read clocks, never data or keys.
        """
        self._check_open()
        if deadline_s is None:
            deadline_s = (self._manager.default_deadline_s
                          if self._manager is not None else None)
            if deadline_s is None:
                deadline_s = default_deadline_s()
        journal = release_journal
        state = charge = None
        if tenant is not None:
            if accountant is not None:
                raise ValueError(
                    "pass either tenant= or accountant=, not both")
            if epsilon is None:
                raise ValueError("tenant queries need epsilon= (the "
                                 "slice charged to the tenant's ledger)")
            state = self.tenant(tenant)
            # Charge-before-run (the at-most-once stance): the slice is
            # spent before any work happens — and exactly refunded below
            # if the query dies before its release token commits.
            charge = state.ledger.charge(
                epsilon, delta, note=f"query seed={seed}",
                window=(_live.window_tag if _live is not None else None))
            accountant = budget_accounting.NaiveBudgetAccountant(
                epsilon, delta)
            if journal is None:
                journal = state.release_journal
        elif accountant is None:
            if epsilon is None:
                raise ValueError(
                    "pass epsilon= (and delta=), an accountant=, or a "
                    "tenant=")
            accountant = budget_accounting.NaiveBudgetAccountant(
                epsilon, delta)
        shn = (self._secure_host_noise
               if secure_host_noise is None else secure_host_noise)
        engine = jax_engine.JaxDPEngine(
            accountant,
            seed=seed,
            secure_host_noise=shn,
            mesh=self._mesh,
            stream_chunks=(_live.view.n_chunks if _live is not None
                           else self._wire.n_chunks),
            segment_sort=self._segment_sort,
            compact_merge=self._compact_merge,
            epilogue_cache=self._epilogue_cache,
            release_journal=journal,
            fault_injector=fault_injector,
            watchdog_timeout_s=watchdog_timeout_s,
            retry_policy=retry_policy)

        deadline = (watchdog_lib.Deadline.after(deadline_s)
                    if deadline_s is not None else None)

        def run_query():
            # Runs on the watchdog worker when a deadline is set; the
            # thread-local hands the Deadline to _accumulate on whatever
            # thread executes the replay.
            self._deadline_tls.value = deadline
            try:
                target = self if _live is None else _live.view
                result = engine.aggregate(
                    target, params, public_partitions=self._public,
                    out_explain_computation_report=(
                        out_explain_computation_report))
                accountant.compute_budgets()
                result.to_columns()  # materialize: replay + finalize
                return result
            finally:
                self._deadline_tls.value = None

        gate = (self._manager.admission()
                if self._manager is not None else contextlib.nullcontext())
        qid = _next_query_id()
        # Slow-query capture bookkeeping (only when a capture dir is
        # configured — the disabled path pays two None checks): the
        # flight watermark and event-counter snapshot scope the capture
        # to THIS query (taken before query_start so the slice holds
        # the full lifecycle).
        cap_dir = obs_flight.capture_dir()
        cap_mark = obs_flight.recorder().watermark() if cap_dir else 0
        cap_events0 = (obs_metrics.default_registry().event_values()
                       if cap_dir else None)
        obs_flight.record("query_start", qid=qid, session=self._name,
                          seed=seed, tenant=tenant or "",
                          deadline_s=deadline_s)
        t_q0 = time.perf_counter()
        root_span = None
        try:
            with obs_trace.span("serving/query", session=self._name,
                                seed=seed, tenant=tenant or "",
                                n_metrics=len(params.metrics),
                                qid=qid) as root_span:
                with contextlib.ExitStack() as stack:
                    with obs_trace.span(
                            "serving/admission",
                            managed=self._manager is not None):
                        stack.enter_context(gate)
                    if deadline is None:
                        result = run_query()
                    else:
                        result = self._run_with_deadline(
                            run_query, deadline, seed, root_span)
        except BaseException as exc:
            if isinstance(exc, watchdog_lib.QueryDeadlineError):
                profiler.count_event(EVENT_DEADLINE_HITS)
            self._maybe_refund(state, charge, journal, engine, exc)
            outcome = self._failure_outcome(exc)
            duration_s = time.perf_counter() - t_q0
            if outcome == "refunded":
                # An unhandled engine error (not a typed fleet outcome):
                # leave the flight-recorder post-mortem while the ring
                # still holds the failing query's events.
                obs_flight.dump_now("engine_error")
            self._finish_query_obs(
                engine=engine, params=params, tenant=tenant,
                accountant=accountant, seed=seed, outcome=outcome,
                duration_s=duration_s, qid=qid)
            self._maybe_capture(qid, root_span, outcome, duration_s,
                                deadline_s, cap_dir, cap_mark,
                                cap_events0, seed=seed, tenant=tenant)
            raise
        duration_s = time.perf_counter() - t_q0
        self._finish_query_obs(
            engine=engine, params=params, tenant=tenant,
            accountant=accountant, seed=seed, outcome="released",
            duration_s=duration_s, qid=qid,
            cols=result.to_columns())
        self._maybe_capture(qid, root_span, "released", duration_s,
                            deadline_s, cap_dir, cap_mark, cap_events0,
                            seed=seed, tenant=tenant)
        if trace_path is not None and root_span is not None:
            tracer = obs_trace.active()
            if tracer is not None:
                tracer.write_chrome(trace_path,
                                    trace_id=root_span.trace_id)
        with self._lock:
            self._queries += 1
        profiler.count_event(EVENT_QUERIES)
        return result

    def _run_with_deadline(self, run_query, deadline, seed,
                           parent_span=None):
        """The whole query under a DispatchWatchdog whose budget is the
        remaining deadline: a wedged replay (which never reaches the
        driver's cooperative between-window check) is abandoned and
        surfaced as QueryDeadlineError within the deadline."""
        wd = watchdog_lib.DispatchWatchdog(
            max(deadline.remaining_s(), 1e-3))
        parent_sinks = profiler.current_sinks()

        def guarded():
            # The watchdog worker joins the query's stage-time sinks AND
            # its span tree (cross-thread parent handoff).
            with profiler.adopt_sinks(parent_sinks), \
                    obs_trace.attach(parent_span):
                return run_query()

        try:
            return wd.call(f"query (session {self._name!r}, seed={seed})",
                           guarded)
        except watchdog_lib.QueryDeadlineError:
            raise  # the driver's cooperative check, already typed
        except watchdog_lib.DispatchHangError as exc:
            raise watchdog_lib.QueryDeadlineError(
                exc.what, deadline.total_s,
                postmortem=exc.postmortem) from exc
        finally:
            wd.close()

    def _maybe_refund(self, state, charge, journal, engine, exc) -> None:
        """Exact refund of a charge whose query provably released
        nothing (SERVING.md "Fleet operation" failure isolation):

        * a refused replay (DoubleReleaseError) drew nothing in THIS
          query — refund;
        * a deadline abandonment might still commit+draw on the
          abandoned worker — conservatively keep the charge;
        * otherwise the release token is checked against the journal:
          not committed means no noise was drawn — refund.
        """
        if state is None or charge is None:
            return
        if isinstance(exc, journal_lib.DoubleReleaseError):
            state.ledger.refund(charge)
            return
        if isinstance(exc, watchdog_lib.QueryDeadlineError):
            return
        token = finalize_ops.release_token(engine._key_stream.fingerprint(),
                                           engine._key_stream.counter)
        if journal is None or not journal.has(token):
            state.ledger.refund(charge)

    @staticmethod
    def _failure_outcome(exc) -> str:
        """The audit-trail outcome of a failed query (obs/audit.py
        OUTCOMES): every failure that refunds reads ``refunded``; the
        typed fleet failures keep their own names."""
        if isinstance(exc, journal_lib.DoubleReleaseError):
            return "double-release-refused"
        if isinstance(exc, watchdog_lib.QueryDeadlineError):
            return "deadline-expired"
        from pipelinedp_tpu.serving import manager as manager_lib
        if isinstance(exc, manager_lib.SessionOverloadedError):
            return "shed"
        return "refunded"

    def _finish_query_obs(self, *, engine, params, tenant, accountant,
                          seed, outcome, duration_s, qid="",
                          cols=None) -> None:
        """One query's telemetry epilogue: the e2e latency observation,
        the flight-recorder outcome event, and the audit record (which
        carries ``qid`` as its ``trace_id`` correlation key). ``cols``
        (released columns) is only present for the ``released``
        outcome; kept/dropped counts are read off the DP output
        (already-released information), never off raw data. -1 marks
        "query produced no output"."""
        obs_metrics.query_seconds().observe(duration_s, outcome=outcome)
        obs_flight.record("query_finish", qid=qid, session=self._name,
                          outcome=outcome,
                          duration_ms=round(duration_s * 1000.0, 3))
        kept = dropped = -1
        if cols is not None:
            keep = np.asarray(cols["keep_mask"])
            kept = int(keep.sum())
            dropped = int(keep.size) - kept
        token = finalize_ops.release_token(
            engine._key_stream.fingerprint(), engine._key_stream.counter)
        self._audit.record(
            session=self._name, tenant=tenant, token=str(token),
            outcome=outcome,
            mechanisms=[str(m) for m in params.metrics],
            noise_kind=getattr(params.noise_kind, "value",
                               str(params.noise_kind)),
            epsilon=float(accountant.total_epsilon),
            delta=float(accountant.total_delta),
            partitions_kept=kept, partitions_dropped=dropped,
            duration_s=duration_s, seed=seed, trace_id=qid)

    def _maybe_capture(self, qid, root_span, outcome, duration_s,
                       deadline_s, cap_dir, cap_mark, cap_events0, *,
                       seed, tenant) -> None:
        """Slow-query capture (OBSERVABILITY.md "Operational plane"): a
        query that exceeded PIPELINEDP_TPU_SLOW_QUERY_S, or landed
        within 20% of its deadline (expired ones included), writes a
        full post-hoc bundle — Chrome trace (when tracing is on),
        metrics delta, flight-recorder slice — into the bounded capture
        directory, named by the query's trace id. Purely a read of
        already-recorded telemetry: it cannot change released bits, and
        write failures are swallowed (a capture is never worth a
        query)."""
        if cap_dir is None:
            return
        slow_s = obs_flight.slow_query_threshold_s()
        near_deadline = (deadline_s is not None
                         and duration_s >= 0.8 * float(deadline_s))
        if not ((slow_s is not None and duration_s >= slow_s)
                or near_deadline):
            return
        events_after = obs_metrics.default_registry().event_values()
        before = cap_events0 or {}
        metrics_delta = {k: v - before.get(k, 0)
                         for k, v in events_after.items()
                         if v != before.get(k, 0)}
        chrome = None
        tracer = obs_trace.active()
        if tracer is not None and root_span is not None:
            chrome = tracer.export_chrome(trace_id=root_span.trace_id)
        document = {
            "version": 1,
            "trace_id": qid,
            "session": self._name,
            "seed": seed,
            "tenant": tenant,
            "outcome": outcome,
            "duration_s": duration_s,
            "deadline_s": deadline_s,
            "slow_query_s": slow_s,
            "near_deadline": near_deadline,
            "metrics_delta": metrics_delta,
            "flight_events": [e.to_payload() for e in
                              obs_flight.recorder().events(
                                  since_seq=cap_mark)],
            "chrome_trace": chrome,
        }
        path = obs_flight.write_capture(qid, document, cap_dir)
        if path is not None:
            profiler.count_event(EVENT_SLOW_CAPTURES)
            obs_flight.record("slow_query_capture", qid=qid, path=path)

    # -- batched queries -------------------------------------------------

    _BATCH_UNSUPPORTED = (
        "batched resident queries support the scalar metrics "
        "(COUNT/PRIVACY_ID_COUNT/SUM/MEAN/VARIANCE); run {} through "
        "session.query instead")

    def _prepare_query(self, index: int, cfg: QueryConfig,
                       secure_host_noise: Optional[bool]) -> _PreparedQuery:
        params = cfg.to_params()
        if any(m.is_percentile for m in params.metrics):
            raise NotImplementedError(
                self._BATCH_UNSUPPORTED.format("PERCENTILE"))
        if Metrics.VECTOR_SUM in params.metrics:
            raise NotImplementedError(
                self._BATCH_UNSUPPORTED.format("VECTOR_SUM"))
        journal = None
        state = charge = None
        if cfg.tenant is not None:
            state = self.tenant(cfg.tenant)
            charge = state.ledger.charge(
                cfg.epsilon, cfg.delta,
                note=f"batch query #{index} seed={cfg.seed}")
            accountant = budget_accounting.NaiveBudgetAccountant(
                cfg.epsilon, cfg.delta)
            journal = state.release_journal
        else:
            accountant = budget_accounting.NaiveBudgetAccountant(
                cfg.epsilon, cfg.delta)
        shn = (self._secure_host_noise
               if secure_host_noise is None else secure_host_noise)
        engine = jax_engine.JaxDPEngine(
            accountant, seed=cfg.seed, secure_host_noise=shn,
            mesh=self._mesh, epilogue_cache=self._epilogue_cache,
            release_journal=journal)
        # Budget-request order replays engine.aggregate exactly, so the
        # per-mechanism (eps, delta) splits are identical to a sequential
        # run of the same config.
        with accountant.scope(weight=params.budget_weight):
            compound = combiners_lib.create_compound_combiner(
                params, accountant)
            sel_spec = None
            if (self._public is None
                    and not params.post_aggregation_thresholding):
                sel_spec = accountant.request_budget(
                    mechanism_type=MechanismType.GENERIC)
            accountant._compute_budget_for_aggregation(params.budget_weight)
        key = engine._key_stream.next_key()
        key_counter = engine._key_stream.counter
        k_kernel, k_select, k_noise = jax.random.split(key, 3)
        linf_cap, l0_cap, l1_cap = jax_engine.derive_contribution_caps(
            params, compound, self.n_rows, self.num_partitions)
        row_lo, row_hi, glo, ghi, middle = jax_engine.derive_clip_bounds(
            params)
        return _PreparedQuery(
            index=index, engine=engine, accountant=accountant,
            compound=compound, sel_spec=sel_spec, params=params,
            k_kernel=k_kernel, k_select=k_select, k_noise=k_noise,
            key_counter=key_counter, linf_cap=linf_cap, l0_cap=l0_cap,
            l1_cap=l1_cap,
            row_lo=row_lo, row_hi=row_hi, glo=glo, ghi=ghi, middle=middle,
            need_flags=jax_engine.derive_need_flags(compound),
            has_group_clip=bool(params.bounds_per_partition_are_set),
            state=state, charge=charge)

    def query_batch(self,
                    configs: Sequence[QueryConfig],
                    *,
                    secure_host_noise: Optional[bool] = None,
                    max_width: Optional[int] = None) -> List[dict]:
        """Answers a batch of queries through the query plane
        (serving/planner.py, SERVING.md "Query plane"): the batch is
        compiled to a QueryPlan before any launch — configs whose
        resolved-sampler bound key is already cached skip replay
        entirely, duplicate configs collapse onto one replay lane, and
        the surviving lanes fuse into vmapped launch groups keyed on
        their kernel statics (at most ``max_width`` /
        PIPELINEDP_TPU_SERVING_BATCH lanes per launch). Per-config
        finalizes run on a bounded executor
        (PIPELINEDP_TPU_EPILOGUE_WORKERS) pipelined behind the next
        group's replay; each config commits its release token before
        any noise draw, under its own keys, budget, and journal.

        Works on single-device and mesh sessions. Returns one released
        column dict per config, in input order — value-for-value what
        ``session.query`` (and therefore a cold engine run) releases
        for that config alone, at any executor width.
        """
        self._check_open()
        self.verify_source()
        width = max_width or batch_width()
        shn = (self._secure_host_noise
               if secure_host_noise is None else secure_host_noise)
        gate = (self._manager.admission()
                if self._manager is not None else contextlib.nullcontext())
        # One trace id for the whole batched launch: every config's
        # audit record correlates to the same batch (they share the
        # wire, the launch groups, and the failure domain).
        qid = _next_query_id()
        obs_flight.record("query_batch_start", qid=qid,
                          session=self._name, n_configs=len(configs))
        t_b0 = time.perf_counter()
        with obs_trace.span("serving/query_batch", session=self._name,
                            n_configs=len(configs),
                            qid=qid) as batch_span, \
                gate, self._pinned():
            prepared: List[_PreparedQuery] = []
            results: List[Optional[dict]] = [None] * len(configs)
            try:
                for i, cfg in enumerate(configs):
                    prepared.append(
                        self._prepare_query(i, cfg, secure_host_noise))
                plan, cached_results = self._plan_batch(prepared, width)
                self._execute_plan(plan, prepared, cached_results,
                                   results, shn, batch_span, t_b0)
            except BaseException as exc:
                # Exact refunds for every tenant config whose release
                # token never committed (the failed launch group and any
                # group that never ran); finished configs keep their
                # charge — their releases are out the door.
                for p in prepared:
                    if p.charge is not None and p.state is not None:
                        token = finalize_ops.release_token(
                            p.engine._key_stream.fingerprint(),
                            p.key_counter)
                        if not p.state.release_journal.has(token):
                            p.state.ledger.refund(p.charge)
                self._audit_batch(configs, prepared, results,
                                  time.perf_counter() - t_b0, exc, qid)
                raise
        self._audit_batch(configs, prepared, results,
                          time.perf_counter() - t_b0, None, qid)
        with self._lock:
            self._queries += len(prepared)
        profiler.count_event(EVENT_QUERIES, len(prepared))
        return results  # type: ignore[return-value]

    def _audit_batch(self, configs, prepared, results, duration_s,
                     exc, qid="") -> None:
        """One audit record per prepared batch config. A config whose
        released columns landed in ``results`` (or whose tenant journal
        holds its token) reads ``released``; the rest take the batch
        failure's outcome. Each record carries the config's OWN
        duration (batch start -> its epilogue completion) when it
        finished; configs that never finished record the batch wall
        time."""
        outcome_on_failure = (self._failure_outcome(exc)
                              if exc is not None else "refunded")
        for p in prepared:
            cfg = configs[p.index]
            token = finalize_ops.release_token(
                p.engine._key_stream.fingerprint(), p.key_counter)
            cols = results[p.index]
            released = cols is not None or (
                p.state is not None
                and p.state.release_journal.has(token))
            kept = dropped = -1
            if cols is not None:
                keep = np.asarray(cols["keep_mask"])
                kept = int(keep.sum())
                dropped = int(keep.size) - kept
            self._audit.record(
                session=self._name, tenant=cfg.tenant, token=str(token),
                outcome="released" if released else outcome_on_failure,
                mechanisms=[str(m) for m in cfg.metrics],
                noise_kind=getattr(cfg.noise_kind, "value",
                                   str(cfg.noise_kind)),
                epsilon=float(cfg.epsilon), delta=float(cfg.delta),
                partitions_kept=kept, partitions_dropped=dropped,
                duration_s=(p.duration_s if p.duration_s is not None
                            else duration_s),
                seed=cfg.seed, trace_id=qid)

    # -- the query plane (serving/planner.py) ----------------------------

    def _batch_key_prefix(self):
        """Bound-cache key prefix for batched queries (None here; live
        sessions tag entries with the wire fingerprint, matching their
        single-query `_accumulate` override)."""
        return None

    def _batch_kw(self, p: _PreparedQuery) -> dict:
        """The exact kw dict `JaxDPEngine._execute` hands `_accumulate`
        for this config on the resident path — batch bound keys MUST
        alias single-query keys, so this mirrors that call site
        field-for-field (quantile metrics never reach the batch path,
        hence quantile_spec=None)."""
        return dict(
            linf_cap=p.linf_cap, l0_cap=p.l0_cap,
            row_clip_lo=p.row_lo, row_clip_hi=p.row_hi, middle=p.middle,
            group_clip_lo=p.glo, group_clip_hi=p.ghi, l1_cap=p.l1_cap,
            need_flags=p.need_flags, has_group_clip=p.has_group_clip,
            quantile_spec=None, segment_sort=self._segment_sort,
            compact_merge=self._compact_merge)

    def _batch_bound_key(self, p: _PreparedQuery) -> tuple:
        """The bound-cache key `_accumulate_wire` would build for this
        config: a batch cache-skip reads exactly the accumulators the
        sequential query would have read, and a batch lane's insert is
        readable by subsequent single queries."""
        kw = self._batch_kw(p)
        key_fp = checkpoint_lib.key_fingerprint(p.k_kernel)
        kw_for_key = {k: v for k, v in kw.items() if k != "segment_sort"}
        cache_key = self._cache_key(key_fp, kw_for_key) + (
            ("resolved_sampler", self._resolved_sampler(self._mesh, kw)),)
        prefix = self._batch_key_prefix()
        if prefix is not None:
            cache_key = (prefix,) + cache_key
        return cache_key

    def _plan_batch(self, prepared: List[_PreparedQuery], width: int):
        """Compiles the batch into a QueryPlan and fetches the cached
        accumulators of every cache-skip under the lock (so a skip can
        never race an eviction between planning and finalize)."""
        from pipelinedp_tpu.serving import planner as planner_lib
        entries = []
        cached_results: Dict[int, Any] = {}
        with self._lock:
            self._check_open()
            for p in prepared:
                p.bound_key = self._batch_bound_key(p)
                entry = self._bound_cache.get(p.bound_key)
                if entry is not None:
                    self._bound_cache.move_to_end(p.bound_key)
                    cached_results[p.index] = entry.result
                entries.append(planner_lib.PlanEntry(
                    index=p.index, bound_key=p.bound_key,
                    fusion_key=(p.has_group_clip, p.l1_cap is not None),
                    need_flags=tuple(p.need_flags),
                    cached=entry is not None))
        plan = planner_lib.compile_plan(entries, width)
        st = plan.stats
        if st["cache_skips"]:
            profiler.count_event(EVENT_BOUND_HITS, st["cache_skips"])
            profiler.count_event(EVENT_PLANNER_CACHE_SKIPS,
                                 st["cache_skips"])
        if st["lanes"]:
            profiler.count_event(EVENT_BOUND_MISSES, st["lanes"])
        if st["dedupes"]:
            profiler.count_event(EVENT_PLANNER_DEDUPES, st["dedupes"])
        if st["fused_groups"]:
            profiler.count_event(EVENT_PLANNER_GROUPS, st["fused_groups"])
        obs_trace.event("batch_plan", **st)
        with self._lock:
            t = self._planner_totals
            t["batches"] += 1
            for k in ("configs", "cache_skips", "dedupes", "lanes",
                      "fused_groups"):
                t[k] += st[k]
        return plan, cached_results

    def _replay_group_batched(self, group, lanes: List[_PreparedQuery]):
        """One launch group's batched replay (the mesh placement and the
        single-device placement share the call shape)."""
        has_group_clip, has_l1 = group.fusion_key
        kwargs = dict(
            linf_caps=[p.linf_cap for p in lanes],
            l0_caps=[p.l0_cap for p in lanes],
            row_clip_los=[p.row_lo for p in lanes],
            row_clip_his=[p.row_hi for p in lanes],
            middles=[p.middle for p in lanes],
            group_clip_los=[p.glo for p in lanes],
            group_clip_his=[p.ghi for p in lanes],
            l1_caps=[p.l1_cap for p in lanes] if has_l1 else None,
            need_flags=tuple(group.union_flags),
            has_group_clip=has_group_clip)
        keys = [p.k_kernel for p in lanes]
        with obs_trace.span("serving/replay_batched", session=self._name,
                            width=len(lanes), n_chunks=self._wire.n_chunks):
            if self._mesh is not None:
                from pipelinedp_tpu.parallel import sharded
                return sharded.replay_resident_wire_batched(
                    self._mesh, keys, self._wire, **kwargs)
            return streaming.replay_resident_wire_batched(
                keys, self._wire, **kwargs)

    def _lane_accs(self, accs_b, b: int):
        """Lane b's [num_partitions] accumulators out of the batched
        [B, num_partitions] fold; on a mesh the slice is re-laid-out to
        the partition sharding the sequential replay produces."""
        accs = columnar.PartitionAccumulators(*(a[b] for a in accs_b))
        if self._mesh is not None:
            from pipelinedp_tpu.parallel import sharded
            part = jax.sharding.NamedSharding(
                self._mesh, sharded._part_spec(self._mesh))
            accs = columnar.PartitionAccumulators(
                *(jax.device_put(a, part) for a in accs))
        return accs

    def _execute_plan(self, plan, prepared: List[_PreparedQuery],
                      cached_results: Dict[int, Any],
                      results: List[Optional[dict]], shn: bool,
                      batch_span, t_b0: float) -> None:
        """Runs a compiled QueryPlan: cache-skips finalize immediately,
        launch groups replay in plan order, and per-config epilogues run
        on the bounded executor double-buffered behind the NEXT group's
        replay (group g's finalizes overlap group g+1's batched fold; at
        most two groups of epilogue work ride behind the replay).

        Released bits are identical at every executor width: the plan
        fixes every config's keys and its commit-before-draw ordering up
        front, and per-config finalize state (engine, accountant,
        epilogue operands) is never shared. Under secure host noise the
        executor narrows to one FIFO worker so the process-global host
        RNG draws in plan order — deterministic for a given plan.

        A failed group raises here; configs whose epilogue never
        committed a release token are exactly refunded by query_batch's
        except path (in-flight epilogues are drained first, so the
        journal check races nothing).
        """
        from concurrent import futures as futures_lib

        by_index = {p.index: p for p in prepared}
        workers = epilogue_workers()
        if shn:
            workers = min(workers, 1)
        if self._mesh is not None:
            # Mesh sessions run epilogues inline: a worker-thread
            # finalize on sharded accumulators, the next group's
            # shard_map replay, and the lane-slice gathers would
            # dispatch multi-device collectives concurrently, and
            # interleaved collective enqueues across the mesh's device
            # threads can deadlock. Plan-level dedupe/fusion still
            # applies; only the overlap is single-device.
            workers = 0
        parent_sinks = profiler.current_sinks()
        stats_lock = threading.Lock()
        epilogue_s = [0.0]
        replay_s = 0.0

        def finalize_one(p: _PreparedQuery, accs) -> None:
            t0 = time.perf_counter()
            # Cross-thread telemetry handoff: the worker joins the batch
            # caller's stage-time sinks and span tree.
            with profiler.adopt_sinks(parent_sinks), \
                    obs_trace.attach(batch_span):
                p.accountant.compute_budgets()
                # At-most-once: the release token commits before any
                # noise draw, through this config's (tenant) journal.
                p.engine._commit_release(p.key_counter)
                results[p.index] = p.engine._fused_finalize(
                    p.compound, p.params, p.sel_spec, p.k_select,
                    p.k_noise, accs, None, None, self.num_partitions,
                    self._public is not None)
            now = time.perf_counter()
            p.duration_s = now - t_b0
            with stats_lock:
                epilogue_s[0] += now - t0

        executor = (futures_lib.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="pdp-epilogue")
            if workers > 0 else None)
        all_futs: List[Any] = []

        def submit(p: _PreparedQuery, accs) -> None:
            if executor is None:
                finalize_one(p, accs)
            else:
                all_futs.append(executor.submit(finalize_one, p, accs))

        try:
            # Cache-skips first: their accumulators are ready now, so
            # their epilogues fill the executor while the first group's
            # replay compiles and runs.
            for idx in plan.cached_indexes:
                submit(by_index[idx], cached_results[idx])
            group_futs: List[List[Any]] = []
            for g, group in enumerate(plan.groups):
                if g >= 2 and executor is not None:
                    # Double-buffer barrier: group g-2's epilogues must
                    # drain before a third replay piles on (bounds the
                    # in-flight accumulator memory to two groups).
                    for f in group_futs[g - 2]:
                        f.result()
                lanes = [by_index[lane.owner] for lane in group.lanes]
                mark = len(all_futs)
                t_r0 = time.perf_counter()
                accs_b = self._replay_group_batched(group, lanes)
                replay_s += time.perf_counter() - t_r0
                for b, lane in enumerate(group.lanes):
                    accs = self._lane_accs(accs_b, b)
                    owner = by_index[lane.owner]
                    if group.flags_exact[b]:
                        # Populate the bound cache FROM the batch: this
                        # launch computed exactly the owner's columns
                        # (union == own flags), so the lane's result is
                        # what a solo replay would have cached.
                        self._cache_insert(owner.bound_key, accs)
                    for idx in lane.indexes:
                        submit(by_index[idx], accs)
                group_futs.append(all_futs[mark:])
            for f in all_futs:
                f.result()
        finally:
            if executor is not None:
                # Failure path: drop queued epilogues (their configs
                # never committed — refunded by the caller) and drain
                # running ones, so the refund's journal check is
                # race-free. Success path: everything already drained.
                executor.shutdown(wait=True, cancel_futures=True)
        wall = time.perf_counter() - t_b0
        with self._lock:
            t = self._planner_totals
            t["replay_s"] += replay_s
            t["epilogue_s"] += epilogue_s[0]
            t["wall_s"] += wall
