"""LiveDatasetSession: crash-exactly-once streaming append with windowed
continual DP releases (SERVING.md "Live sessions").

A batch DatasetSession freezes its dataset at ingest. A live session
accepts **appends** — micro-batches of new rows — while staying durable
and queryable, under three contracts:

  * **Crash-exactly-once append.** Each micro-batch commits through a
    write-ahead discipline: the raw rows land durably first (atomic npz
    under ``epochs/``), then one fsync'd append-WAL record carrying the
    batch's content digest — and *that WAL append is the commit point*.
    SIGKILL at any instant leaves the reopened session
    (``SessionStore.open_live``) at exactly epoch N or N+1, never a torn
    in-between, and re-submitting a batch whose digest the WAL already
    carries is an idempotent no-op — the producer may retry blindly.
  * **Bit-identity to cold.** The fold is a deterministic re-encode of
    the union of committed rows through the very ingest pipeline a cold
    ``DatasetSession`` runs (same pinned chunk count, same mesh bucket
    layout), so every query of the live session — full or windowed — is
    bit-identical to the same query over the same rows ingested cold.
    Appending per-epoch slabs instead would split privacy units across
    buckets (pid-disjoint bucketing is what the chunk kernels' DP
    bounding relies on); the union re-encode keeps the invariant by
    construction, at O(total rows) per append.
  * **At-most-once releases.** Windowed releases ride the existing
    release-token journal: a :class:`ReleaseSchedule` answers each
    sealed window exactly once across restarts — a crash between the
    release and its outcome record is recovered as ``"recovered"``
    (the token refuses to re-draw; the charge is refunded), and a
    deliberate replay of a recorded window surfaces
    ``DoubleReleaseError``.

Event time is the **epoch axis**: each append carries an integer
``event_epoch`` (default: one past the largest seen). The watermark is
driven by the data (plus explicit :meth:`~LiveDatasetSession.
advance_watermark` calls); a batch older than
``watermark - allowed_lateness`` is *late* and is either rejected with a
typed :class:`LateArrivalError` or persisted to the dead-letter
directory — the operator's choice (``WindowSpec.late_policy``). A
window ``[a, b)`` is **sealed** once no acceptable future event can land
in it; only sealed windows are queryable/releasable, which is what makes
their answers deterministic.

Backpressure mirrors query admission: more than ``max_pending_appends``
concurrent appends shed with a typed :class:`IngestOverloadedError`
*before* any durable or budget effect, so a shed append needs no undo.

Constraints (all checked): live sessions are store-bound from birth,
need ``public_partitions`` (the vocabulary must not grow with the data)
and an explicit ``n_chunks`` (the pinned schedule is what makes reopen
deterministic), take numeric columns only (epoch payloads are
``allow_pickle=False`` npz), and skip source verification — each epoch
carries its own content digest instead.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import flight as obs_flight
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.ops import encoding, streaming
from pipelinedp_tpu.runtime import journal as journal_lib
from pipelinedp_tpu.serving.session import DatasetSession

# Tuning knobs (validated via native.loader.env_int; README "Tuning
# knobs" + SERVING.md):
#   PIPELINEDP_TPU_MAX_PENDING_APPENDS — concurrent appends admitted
#     before the ingest gate sheds (default 64). The constructor's
#     max_pending_appends= overrides, including an explicit 0 (shed
#     everything — the backpressure tests use it).
MAX_PENDING_ENV = "PIPELINEDP_TPU_MAX_PENDING_APPENDS"
#   PIPELINEDP_TPU_APPEND_COMMIT_WINDOW_MS — bounded group-commit
#     window (default 0): the fsync leader waits this long so racing
#     appends ride one fsync. 0 still group-commits opportunistically
#     (appends that land while a leader is fsyncing coalesce behind the
#     next leader); >0 trades append latency for fewer fsyncs.
APPEND_COMMIT_WINDOW_ENV = "PIPELINEDP_TPU_APPEND_COMMIT_WINDOW_MS"
# Test seam for the kill harness (tests/kill_harness.py): "<stage>" or
# "<stage>@<n>" SIGKILLs the process at that append/release stage —
# "encode" fires before the WAL record is written (reopen lands at
# epoch N), "commit" after the record is written+flushed but before the
# group fsync (the page cache survives SIGKILL, so reopen lands at N+1;
# only power loss could tear it), "fold" after the fsync barrier
# (reopen lands at N+1), "release" between a scheduled window's release
# and its outcome record (catch-up recovers it).
LIVE_CRASH_ENV = "PIPELINEDP_TPU_LIVE_CRASH"

# Profiler event counters (profiler.count_event / event_count):
EVENT_APPENDS = "serving/appends"
EVENT_APPEND_DUPLICATES = "serving/append_duplicates"
EVENT_APPENDS_SHED = "serving/appends_shed"
EVENT_LATE_REJECTED = "serving/late_arrivals_rejected"
EVENT_LATE_DEADLETTERED = "serving/late_arrivals_deadlettered"
EVENT_EPOCH_FOLDS = "serving/epoch_folds"
EVENT_SCHEDULED_RELEASES = "serving/scheduled_releases"
EVENT_RELEASES_RECOVERED = "serving/scheduled_releases_recovered"
EVENT_RELEASES_SUPPRESSED = "serving/scheduled_releases_suppressed"
# Appends refused by the single-writer fence (serving/fleet.py): a
# superseded ex-primary tried to write; the batch is dead-lettered so
# the data is quarantined, never folded under a stale lease.
EVENT_APPENDS_FENCED = "serving/appends_fenced"


def max_pending_appends_default() -> int:
    """Validated PIPELINEDP_TPU_MAX_PENDING_APPENDS (default 64)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(MAX_PENDING_ENV, 64, 1, 1 << 16)


def append_commit_window_s() -> float:
    """Validated PIPELINEDP_TPU_APPEND_COMMIT_WINDOW_MS as seconds
    (default 0: opportunistic coalescing only)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(APPEND_COMMIT_WINDOW_ENV, 0, 0, 1000) / 1000.0


def live_counters() -> Dict[str, int]:
    """Snapshot of the live-session counters (bench.py surfaces this)."""
    return {
        "appends": profiler.event_count(EVENT_APPENDS),
        "append_duplicates": profiler.event_count(EVENT_APPEND_DUPLICATES),
        "appends_shed": profiler.event_count(EVENT_APPENDS_SHED),
        "late_arrivals_rejected": profiler.event_count(EVENT_LATE_REJECTED),
        "late_arrivals_deadlettered": profiler.event_count(
            EVENT_LATE_DEADLETTERED),
        "epoch_folds": profiler.event_count(EVENT_EPOCH_FOLDS),
        "scheduled_releases": profiler.event_count(
            EVENT_SCHEDULED_RELEASES),
        "scheduled_releases_recovered": profiler.event_count(
            EVENT_RELEASES_RECOVERED),
        "scheduled_releases_suppressed": profiler.event_count(
            EVENT_RELEASES_SUPPRESSED),
        "appends_fenced": profiler.event_count(EVENT_APPENDS_FENCED),
    }


def _maybe_crash(stage: str, ordinal: int) -> None:
    """The kill-harness seam (LIVE_CRASH_ENV): a real SIGKILL — no
    cleanup, no atexit — at a named stage, optionally only at the
    given append-epoch / window-start ordinal."""
    spec = os.environ.get(LIVE_CRASH_ENV, "")
    if not spec:
        return
    want_stage, _, want_n = spec.partition("@")
    if want_stage != stage:
        return
    if want_n and int(want_n) != ordinal:
        return
    os.kill(os.getpid(), signal.SIGKILL)


class LateArrivalError(RuntimeError):
    """A batch arrived behind the watermark's lateness allowance under
    the "reject" policy: accepting it would mutate windows that may
    already be sealed (and released)."""

    def __init__(self, event_epoch: int, horizon: int):
        super().__init__(
            f"late arrival: event_epoch={event_epoch} is behind the "
            f"lateness horizon {horizon} (watermark minus "
            f"allowed_lateness); the batch was refused — route it to a "
            f"dead-letter flow or configure late_policy='dead_letter'")
        self.event_epoch = event_epoch
        self.horizon = horizon


class IngestOverloadedError(RuntimeError):
    """The append gate is full: this batch is shed, not queued — before
    any durable or budget effect, so retrying it later is safe (and
    idempotent even if a racing duplicate did commit)."""

    def __init__(self, pending: int, max_pending: int):
        super().__init__(
            f"live ingest overloaded: {pending} appends pending (gate "
            f"{max_pending}); batch shed — retry with backoff")
        self.pending = pending
        self.max_pending = max_pending


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """Windowing over the epoch axis.

    size: window length in event epochs; windows are half-open
      ``[a, a + size)``.
    slide: start-to-start distance — ``None`` (tumbling, slide == size)
      or any positive int (sliding; overlapping when < size).
    allowed_lateness: how far behind the largest seen event an append
      may land before it is *late*. A window ``[a, b)`` is sealed once
      ``b <= max_event - allowed_lateness`` — no acceptable future
      event can reach it.
    late_policy: "reject" (typed LateArrivalError) or "dead_letter"
      (the batch persists under the store's dead-letter directory and
      a counter ticks; it never folds).
    """
    size: int
    slide: Optional[int] = None
    allowed_lateness: int = 0
    late_policy: str = "reject"

    def __post_init__(self):
        if self.size < 1:
            raise ValueError(f"window size must be >= 1, got {self.size}")
        if self.slide is not None and self.slide < 1:
            raise ValueError(f"slide must be >= 1, got {self.slide}")
        if self.allowed_lateness < 0:
            raise ValueError(
                f"allowed_lateness must be >= 0, got "
                f"{self.allowed_lateness}")
        if self.late_policy not in ("reject", "dead_letter"):
            raise ValueError(
                f"late_policy must be 'reject' or 'dead_letter', got "
                f"{self.late_policy!r}")

    @property
    def stride(self) -> int:
        return self.slide if self.slide is not None else self.size

    def windows_sealed_by(self, horizon: int) -> List[tuple]:
        """All ``[a, b)`` windows with ``b <= horizon``, in order."""
        out = []
        a = 0
        while a + self.size <= horizon:
            out.append((a, a + self.size))
            a += self.stride
        return out

    def to_meta(self) -> dict:
        return {"size": self.size, "slide": self.slide,
                "allowed_lateness": self.allowed_lateness,
                "late_policy": self.late_policy}

    @classmethod
    def from_meta(cls, meta: dict) -> "WindowSpec":
        return cls(size=meta["size"], slide=meta["slide"],
                   allowed_lateness=meta["allowed_lateness"],
                   late_policy=meta["late_policy"])


@dataclasses.dataclass(frozen=True)
class AppendResult:
    """One append's outcome. ``committed`` is True only when the batch
    became a new epoch; duplicates and dead-lettered batches report
    their identity without mutating the fold."""
    epoch: int  # the committed epoch index (or the duplicate's)
    digest: str
    n_rows: int
    event_epoch: int
    committed: bool
    duplicate: bool = False
    dead_lettered: bool = False


@dataclasses.dataclass
class _LiveBinding:
    """The private contract with DatasetSession.query(_live=...): the
    window's resident-dataset view plus the ledger window tag its
    charge carries (per-window budget caps)."""
    view: Any
    window_tag: Optional[str]


class _WindowView:
    """A sealed window as a resident dataset: duck-types exactly what
    JaxDPEngine._aggregate touches (pk_vocab, n_rows,
    _check_engine_compat, _accumulate) and routes the replay through
    the owning session's wire-parameterized accumulate path — so
    window queries share the bound cache, deadline handoff, and
    OOM-degradation machinery of full-session queries."""

    is_resident_dataset = True

    def __init__(self, session: "LiveDatasetSession", wire, a: int,
                 b: int):
        self._session = session
        self._wire = wire
        self._bounds = (a, b)

    @property
    def pk_vocab(self):
        return self._session.pk_vocab

    @property
    def n_rows(self) -> int:
        # The engine derives contribution caps from n_rows: it must be
        # the WINDOW's row count for cold-parity, not the session's.
        return self._wire.n_rows

    @property
    def num_partitions(self) -> int:
        return self._wire.num_partitions

    @property
    def n_chunks(self) -> int:
        # The pinned schedule, not wire.n_chunks: an empty window's
        # wire has zero buckets but the cold-parity engine still wants
        # the session's chunk count.
        return self._session.live_n_chunks

    def _check_engine_compat(self, engine, public_partitions) -> None:
        self._session._check_engine_compat(engine, public_partitions)

    def _accumulate(self, k_kernel, *, mesh, resilience=None, **kw):
        a, b = self._bounds
        return self._session._accumulate_wire(
            self._wire, ("window", a, b, self._wire.fingerprint),
            k_kernel, mesh=mesh, resilience=resilience, **kw)


class LiveDatasetSession(DatasetSession):
    """A DatasetSession that grows by appends (module docstring).

    Create with :meth:`create` (store-bound from birth); reopen after
    process death with ``SessionStore.open_live`` — never the batch
    ``open``, which refuses live sessions because their authoritative
    state is the append WAL plus epoch payloads, not the wire spill.
    """

    @classmethod
    def create(cls, *, store, name: str,
               public_partitions: Sequence[Any],
               n_chunks: int,
               window: WindowSpec,
               mesh=None,
               resident_bytes: Optional[int] = None,
               secure_host_noise: bool = True,
               segment_sort="auto",
               compact_merge="auto",
               epilogue_cache=None,
               max_pending_appends: Optional[int] = None
               ) -> "LiveDatasetSession":
        """An empty live session, durably registered in ``store`` before
        it returns (epoch 0 exists on disk the instant create does)."""
        if public_partitions is None:
            raise ValueError(
                "live sessions need public_partitions: the partition "
                "vocabulary is fixed at creation — a vocabulary that "
                "grew with appended data would leak which partitions "
                "arrived")
        if n_chunks is None or int(n_chunks) < 1:
            raise ValueError(
                "live sessions need an explicit n_chunks >= 1: the "
                "pinned chunk schedule is what makes every fold and "
                "reopen bit-deterministic")
        if store is None:
            raise ValueError(
                "live sessions are store-bound from birth (the append "
                "WAL and epoch payloads live under the store); pass "
                "store=")
        vocab = encoding.Vocabulary(list(public_partitions))
        n_dev = mesh.devices.size if mesh is not None else 1
        self = cls._restore(
            dataclasses.replace(
                streaming._empty_resident_wire(max(len(vocab), 1)),
                n_dev=n_dev),
            vocab,
            public_partitions=public_partitions, mesh=mesh, name=name,
            secure_host_noise=secure_host_noise,
            segment_sort=segment_sort, compact_merge=compact_merge,
            resident_bytes=resident_bytes, epilogue_cache=epilogue_cache,
            store_binding=None)
        self._init_live(window, int(n_chunks), max_pending_appends)
        # Single-writer from birth: the lease is taken BEFORE any
        # durable state exists, so a concurrent create/open of the same
        # name is refused instead of interleaved (serving/fleet.py).
        lease = store._acquire_lease(name, None, False)
        try:
            # Durable birth: wire spill + manifest, then the live
            # section — register_tenant and open_live both need the
            # manifest to exist.
            self._store_binding = (store, name)
            self.save(store)
            store.record_live(name, self._live_meta())
            self._wal = journal_lib.JsonlWal(store.append_wal_path(name))
            self._attach_lease(lease)
        except BaseException:
            lease.release()
            raise
        return self

    def _init_live(self, window: WindowSpec, n_chunks: int,
                   max_pending_appends: Optional[int]) -> None:
        self._live_window = window
        self._live_n_chunks = n_chunks
        self._max_pending = (int(max_pending_appends)
                             if max_pending_appends is not None
                             else max_pending_appends_default())
        self._append_lock = threading.Lock()
        self._pending = 0
        self._pending_lock = threading.Lock()
        # One dict per committed epoch, in commit order: {"epoch",
        # "digest", "n_rows", "event_epoch"}; rows retained raw for the
        # union fold and window views.
        self._epochs: List[dict] = []
        self._epoch_rows: Dict[int, tuple] = {}
        self._digests: Dict[str, int] = {}  # content digest -> epoch
        self._deadletters: set = set()
        self._max_event = -1
        self._has_value: Optional[bool] = None
        self._window_wires: Dict[tuple, Any] = {}
        self._wal: Optional[journal_lib.JsonlWal] = None
        # Group-commit state (SERVING.md "The append commit point"):
        # epochs are assigned and WAL-written under _append_lock, then
        # *staged* until the group fsync covers their WAL ticket; only
        # then do they promote (in epoch order) into _epochs. The fold
        # coalesces: one union re-encode may cover several promotions.
        self._next_epoch = 0
        self._staged: Dict[int, dict] = {}        # epoch -> staged rec
        self._staged_digests: Dict[str, dict] = {}  # digest -> same rec
        self._fold_lock = threading.Lock()
        self._folded_epochs = 0
        # Replication cursor (serving/fleet.py FollowerSession): how
        # many append-WAL records this session's state reflects —
        # recovered count on a writable reopen, poll-applied count on a
        # read-only follower.
        self._applied_wal_records = 0

    # -- identity & status ------------------------------------------------

    @property
    def epoch(self) -> int:
        """Committed epoch count — the append-WAL's append-record count."""
        return len(self._epochs)

    @property
    def watermark(self) -> int:
        """One past the largest event epoch seen (0 while empty)."""
        return self._max_event + 1

    @property
    def sealed_horizon(self) -> int:
        """Windows ending at or before this are sealed: no acceptable
        future event can land in them."""
        return self._max_event - self._live_window.allowed_lateness

    @property
    def window_spec(self) -> WindowSpec:
        return self._live_window

    @property
    def live_n_chunks(self) -> int:
        """The pinned per-fold chunk schedule (explicit at create)."""
        return self._live_n_chunks

    def sealed_windows(self) -> List[tuple]:
        """All currently sealed ``[a, b)`` windows, in order."""
        return self._live_window.windows_sealed_by(self.sealed_horizon)

    def is_sealed(self, a: int, b: int) -> bool:
        return b <= self.sealed_horizon

    def live_status(self) -> dict:
        """The live plane of :meth:`stats` — epoch, watermark, window
        configuration, gate pressure (ops_plane /statusz surfaces it)."""
        with self._lock:
            return {
                "epoch": len(self._epochs),
                "max_event": self._max_event,
                "watermark": self._max_event + 1,
                "sealed_horizon": (self._max_event
                                   - self._live_window.allowed_lateness),
                "sealed_windows": len(self.sealed_windows()),
                "window": self._live_window.to_meta(),
                "n_chunks": self._live_n_chunks,
                "pending_appends": self._pending,
                "max_pending_appends": self._max_pending,
                "deadletters": len(self._deadletters),
                "wire_fingerprint": self._wire.fingerprint,
                "role": ("follower" if self._read_only else "primary"),
                "applied_wal_records": self._applied_wal_records,
            }

    def stats(self) -> dict:
        out = super().stats()
        out["live"] = self.live_status()
        return out

    def _live_meta(self) -> dict:
        return {"window": self._live_window.to_meta(),
                "n_chunks": self._live_n_chunks}

    # -- fleet tier (serving/fleet.py) ------------------------------------

    @property
    def applied_wal_records(self) -> int:
        """How many append-WAL records this session's state reflects —
        the follower's replication cursor."""
        with self._lock:
            return self._applied_wal_records

    def _attach_lease(self, lease) -> None:
        """Live sessions don't just hold the lease — they FENCE every
        WAL with it: the append WAL and each tenant's release/ledger
        journals re-check the on-disk lease per append and embed the
        fencing token in the record, so a superseded writer is refused
        at the journal (StaleWriterError), not merely raced."""
        super()._attach_lease(lease)
        fence = lease.admit
        if self._wal is not None:
            self._wal.attach_fence(fence)
        with self._lock:
            tenant_states = list(self._tenants.values())
        for state in tenant_states:
            self._fence_tenant(state, fence)

    @staticmethod
    def _fence_tenant(state, fence) -> None:
        for journal in (state.release_journal, state.ledger._wal):
            if hasattr(journal, "attach_fence"):
                journal.attach_fence(fence)

    def register_tenant(self, *args, **kwargs):
        state = super().register_tenant(*args, **kwargs)
        fence = self._wal_fence()
        if fence is not None:
            self._fence_tenant(state, fence)
        return state

    def apply_wal_payloads(self, payloads) -> None:
        """Folds already-committed append-WAL payloads into a READ-ONLY
        replica (FollowerSession.poll). Each "append" record's epoch
        payload is loaded digest-validated against the record; the
        replica's wire refolds once per batch of records. Refuses on a
        writable session — the primary's own append path owns its
        state."""
        if not self._read_only:
            raise RuntimeError(
                "apply_wal_payloads is the follower replication path; "
                "a writable session folds through append()")
        store, name = self._store_binding
        applied = 0
        for payload in payloads:
            self._apply_wal_payload(payload, store, name)
            applied += 1
        if applied == 0:
            return
        with self._lock:
            self._applied_wal_records += applied
            self._next_epoch = len(self._epochs)
        self._deadletters = set(store.deadletter_digests(name))
        old_fp = self._wire.fingerprint
        new_wire = self._fold_union()
        with self._lock:
            self._wire = new_wire
            self._folded_epochs = len(self._epochs)
            self._sweep_stale_bound_entries(old_fp)
        if (self._mesh is None and new_wire.n_rows > 0
                and new_wire.host_nbytes <= self._byte_budget):
            new_wire.ensure_device()

    def _apply_wal_payload(self, payload: dict, store, name) -> None:
        """Applies one append-WAL record to the in-memory epoch maps
        (shared by the writable _reopen replay and the follower poll;
        the caller refolds the wire afterwards)."""
        kind = payload.get("kind")
        if kind == "advance":
            with self._lock:
                self._max_event = max(self._max_event,
                                      int(payload["event_epoch"]))
            return
        if kind != "append":
            raise journal_lib.JournalCorruptError(
                f"session {name!r}: append-WAL record "
                f"{payload.get('seq')} has unknown kind {kind!r}")
        epoch = int(payload["epoch"])
        digest = payload["content_digest"]
        pid, pk, value = store.load_epoch(name, epoch, digest)
        with self._lock:
            self._epochs.append({
                "epoch": epoch, "digest": digest,
                "n_rows": int(payload["n_rows"]),
                "event_epoch": int(payload["event_epoch"])})
            self._epoch_rows[epoch] = (pid, pk, value)
            self._digests[digest] = epoch
            self._max_event = max(self._max_event,
                                  int(payload["event_epoch"]))
            if self._has_value is None:
                self._has_value = value is not None

    # -- append: the crash-exactly-once transaction -----------------------

    def append(self, pid, pk, value=None, *,
               event_epoch: Optional[int] = None) -> AppendResult:
        """Appends one micro-batch as the next epoch (module docstring
        for the commit discipline). Returns an :class:`AppendResult`;
        re-submitting a committed batch (same content digest) is an
        idempotent no-op reporting ``duplicate=True``.

        ``event_epoch`` places the batch on the window axis (default:
        one past the largest seen — strictly in-order arrival). A batch
        behind ``watermark - allowed_lateness`` follows the late
        policy; an empty batch is refused (advance the watermark with
        :meth:`advance_watermark` instead — an empty append has no
        digest identity to make idempotent).
        """
        self._ensure_writable("append()")
        with self._pending_lock:
            if self._pending >= self._max_pending:
                profiler.count_event(EVENT_APPENDS_SHED)
                obs_trace.event("append_shed", pending=self._pending,
                                max_pending=self._max_pending)
                raise IngestOverloadedError(self._pending,
                                            self._max_pending)
            self._pending += 1
        t0 = time.perf_counter()
        try:
            return self._append_locked(pid, pk, value, event_epoch, t0)
        finally:
            with self._pending_lock:
                self._pending -= 1

    def _append_locked(self, pid, pk, value, event_epoch,
                       t0) -> AppendResult:
        pid = np.asarray(pid)
        pk = np.asarray(pk)
        value = None if value is None else np.asarray(value)
        n = len(pid)
        if n == 0:
            raise ValueError(
                "empty append: an empty batch has no content identity "
                "to dedup on; use advance_watermark to move event time "
                "without rows")
        if len(pk) != n or (value is not None and len(value) != n):
            raise ValueError(
                f"column lengths disagree: pid={n} pk={len(pk)}"
                + (f" value={len(value)}" if value is not None else ""))
        for col_name, col in (("pid", pid), ("pk", pk),
                              ("value", value)):
            if col is not None and col.dtype.kind not in "iuf":
                raise ValueError(
                    f"live appends take numeric columns only "
                    f"({col_name} has dtype {col.dtype}); epoch "
                    f"payloads are allow_pickle=False npz")
        digest = streaming.input_digest(pid, pk, value)
        store, name = self._store_binding
        # Phase A (under _append_lock): validate, write the epoch
        # payload + WAL record (flushed, not yet fsync'd), stage. The
        # fsync itself happens OUTSIDE the lock so concurrent appends
        # coalesce behind one group commit instead of serializing on
        # per-record fsyncs.
        dup_staged = None
        with self._append_lock:
            self._check_open()
            # Idempotency FIRST — before event assignment, so a blind
            # re-submit of a committed batch never re-enters as a new
            # (possibly late) event. Promotion mutates the committed
            # maps under self._lock, so read them under it too.
            with self._lock:
                prior_epoch = self._digests.get(digest)
                prior = (self._epochs[prior_epoch]
                         if prior_epoch is not None else None)
                if prior is None:
                    dup_staged = self._staged_digests.get(digest)
                dead = digest in self._deadletters
                eff_max_event = self._max_event
                has_value = self._has_value
                for rec in self._staged.values():
                    eff_max_event = max(eff_max_event,
                                        rec["event_epoch"])
                    if has_value is None:
                        has_value = rec["value_present"]
            if prior is not None:
                profiler.count_event(EVENT_APPEND_DUPLICATES)
                obs_trace.event("append_duplicate", digest=digest)
                obs_metrics.append_seconds().observe(
                    time.perf_counter() - t0)
                return AppendResult(
                    epoch=prior_epoch, digest=digest,
                    n_rows=prior["n_rows"],
                    event_epoch=prior["event_epoch"], committed=False,
                    duplicate=True)
            if dead:
                profiler.count_event(EVENT_APPEND_DUPLICATES)
                obs_metrics.append_seconds().observe(
                    time.perf_counter() - t0)
                return AppendResult(
                    epoch=-1, digest=digest, n_rows=n,
                    event_epoch=(event_epoch if event_epoch is not None
                                 else -1),
                    committed=False, duplicate=True, dead_lettered=True)
            if dup_staged is None:
                if event_epoch is None:
                    event_epoch = eff_max_event + 1
                event_epoch = int(event_epoch)
                if event_epoch < 0:
                    raise ValueError(
                        f"event_epoch must be >= 0, got {event_epoch}")
                horizon = (eff_max_event
                           - self._live_window.allowed_lateness)
                if event_epoch < horizon:
                    return self._handle_late(store, name, digest, pid,
                                             pk, value, event_epoch,
                                             horizon, t0)
                if value is not None and has_value is False or \
                        value is None and has_value is True:
                    raise ValueError(
                        "value column presence must be consistent across "
                        "a live session's appends (the union fold encodes "
                        "one value plan)")
                epoch = self._next_epoch
                with obs_trace.span("serving/append", session=self._name,
                                    epoch=epoch, n_rows=n,
                                    event_epoch=event_epoch):
                    obs_flight.record("append_start", session=self._name,
                                      epoch=epoch, digest=digest,
                                      n_rows=n, event_epoch=event_epoch)
                    # Durable payload, then the pre-commit micro-encode:
                    # re-drives the SlabDriver ingest schedule over JUST
                    # the new rows, so rows that cannot encode (value
                    # overflow, bad ids) fail HERE — before the WAL
                    # record exists, leaving the session untouched at
                    # epoch N.
                    store.save_epoch(name, epoch, pid, pk, value)
                    self._micro_encode(pid, pk, value)
                    _maybe_crash("encode", epoch)
                    # The commit record: written + flushed here; durable
                    # against power loss only after the group fsync
                    # below. "digest" is the WAL's own per-record key;
                    # the batch identity travels as content_digest. A
                    # leased WAL's fence re-checks the on-disk lease
                    # HERE — a superseded ex-primary's append is
                    # refused before the record lands.
                    try:
                        self._wal.append({
                            "seq": self._wal.next_seq, "kind": "append",
                            "epoch": epoch, "content_digest": digest,
                            "n_rows": n, "event_epoch": event_epoch},
                            sync=False)
                    except journal_lib.StaleWriterError:
                        self._fenced_append(store, name, digest, pid,
                                            pk, value, event_epoch)
                        raise
                    _maybe_crash("commit", epoch)
                    ticket = self._wal.sync_ticket()
                    staged = {
                        "epoch": epoch, "digest": digest, "n_rows": n,
                        "event_epoch": event_epoch, "ticket": ticket,
                        "rows": (pid, pk, value),
                        "value_present": value is not None}
                    with self._lock:
                        self._staged[epoch] = staged
                        self._staged_digests[digest] = staged
                    self._next_epoch = epoch + 1
        if dup_staged is not None:
            # A racing append already wrote this batch's WAL record but
            # has not fsync'd yet: ride its group commit, then report
            # the duplicate against the promoted epoch.
            self._wal.sync_through(dup_staged["ticket"])
            self._promote_staged()
            profiler.count_event(EVENT_APPEND_DUPLICATES)
            obs_trace.event("append_duplicate", digest=digest)
            obs_metrics.append_seconds().observe(time.perf_counter() - t0)
            return AppendResult(
                epoch=dup_staged["epoch"], digest=digest,
                n_rows=dup_staged["n_rows"],
                event_epoch=dup_staged["event_epoch"], committed=False,
                duplicate=True)
        # Phase B: THE commit point — the group fsync. One leader
        # fsyncs for every staged append up to its ticket (bounded
        # coalescing window via PIPELINEDP_TPU_APPEND_COMMIT_WINDOW_MS).
        # Before it, the epoch does not exist (against power loss);
        # after it, reopen folds it.
        self._wal.sync_through(ticket,
                               window_s=append_commit_window_s())
        _maybe_crash("fold", epoch)
        # Phase C: ordered promotion into the committed maps
        # (idempotent — whichever thread reaches an epoch first
        # promotes it; epochs promote strictly in order).
        self._promote_staged()
        # Phase D: the coalesced union fold — one re-encode may cover
        # several freshly promoted epochs.
        fingerprint = self._fold_committed()
        profiler.count_event(EVENT_APPENDS)
        obs_flight.record("append_commit", session=self._name,
                          epoch=epoch, digest=digest,
                          fingerprint=fingerprint)
        obs_metrics.append_seconds().observe(time.perf_counter() - t0)
        return AppendResult(epoch=epoch, digest=digest, n_rows=n,
                            event_epoch=event_epoch, committed=True)

    def _fenced_append(self, store, name, digest, pid, pk, value,
                       event_epoch) -> None:
        """A fenced (stale-writer) append's bookkeeping: the batch is
        dead-lettered — quarantined data, never a committed epoch under
        a superseded lease — and counted, before the StaleWriterError
        propagates to the producer. The new primary sees the dead
        letter on its next reopen/poll."""
        profiler.count_event(EVENT_APPENDS_FENCED)
        obs_trace.event("append_fenced", digest=digest,
                        event_epoch=event_epoch)
        obs_flight.record("append_fenced", session=self._name,
                          digest=digest, event_epoch=event_epoch)
        try:
            store.save_deadletter(name, digest, pid, pk, value)
            with self._lock:
                self._deadletters.add(digest)
        except OSError:
            pass  # quarantine is best-effort; the refusal is the point

    def _promote_staged(self) -> None:
        """Moves fsync-covered staged epochs into the committed maps,
        strictly in epoch order (any thread may run this; promotion is
        idempotent under self._lock). A staged epoch promotes once the
        WAL's synced ticket covers its record."""
        synced = self._wal.synced_ticket
        while True:
            with self._lock:
                rec = self._staged.get(len(self._epochs))
                if rec is None or rec["ticket"] > synced:
                    return
                epoch = rec["epoch"]
                self._epochs.append({
                    "epoch": epoch, "digest": rec["digest"],
                    "n_rows": rec["n_rows"],
                    "event_epoch": rec["event_epoch"]})
                self._epoch_rows[epoch] = rec["rows"]
                self._digests[rec["digest"]] = epoch
                self._max_event = max(self._max_event,
                                      rec["event_epoch"])
                if self._has_value is None:
                    self._has_value = rec["value_present"]
                del self._staged[epoch]
                self._staged_digests.pop(rec["digest"], None)

    def _fold_committed(self) -> str:
        """The coalesced in-memory fold: re-encodes the union of the
        committed epochs unless a concurrent fold already covered them
        (one union re-encode may serve several promotions). Returns the
        current wire fingerprint."""
        with self._fold_lock:
            with self._lock:
                target = len(self._epochs)
                if self._folded_epochs >= target:
                    return self._wire.fingerprint
            old_fp = self._wire.fingerprint
            new_wire = self._fold_union()
            with self._lock:
                self._wire = new_wire
                self._folded_epochs = target
                self._sweep_stale_bound_entries(old_fp)
            if (self._mesh is None and new_wire.n_rows > 0
                    and new_wire.host_nbytes <= self._byte_budget):
                new_wire.ensure_device()
            profiler.count_event(EVENT_EPOCH_FOLDS)
            return new_wire.fingerprint

    def _handle_late(self, store, name, digest, pid, pk, value,
                     event_epoch, horizon, t0) -> AppendResult:
        if self._live_window.late_policy == "dead_letter":
            store.save_deadletter(name, digest, pid, pk, value)
            with self._lock:
                self._deadletters.add(digest)
            profiler.count_event(EVENT_LATE_DEADLETTERED)
            obs_trace.event("append_deadlettered", digest=digest,
                            event_epoch=event_epoch, horizon=horizon)
            obs_flight.record("append_deadlettered", session=self._name,
                              digest=digest, event_epoch=event_epoch)
            obs_metrics.append_seconds().observe(time.perf_counter() - t0)
            return AppendResult(epoch=-1, digest=digest, n_rows=len(pid),
                                event_epoch=event_epoch, committed=False,
                                dead_lettered=True)
        profiler.count_event(EVENT_LATE_REJECTED)
        obs_trace.event("append_late_rejected", digest=digest,
                        event_epoch=event_epoch, horizon=horizon)
        raise LateArrivalError(event_epoch, horizon)

    def advance_watermark(self, event_epoch: int) -> None:
        """Durably advances event time without rows (e.g. a quiet
        period that should seal — and release — empty windows). The
        advancement is a WAL record, so reopen replays it."""
        self._ensure_writable("advance_watermark()")
        event_epoch = int(event_epoch)
        if event_epoch < 0:
            raise ValueError(
                f"event_epoch must be >= 0, got {event_epoch}")
        with self._append_lock:
            self._check_open()
            if event_epoch <= self._max_event:
                return  # monotone: never move the watermark backwards
            self._wal.append({"seq": self._wal.next_seq,
                              "kind": "advance",
                              "event_epoch": event_epoch})
            with self._lock:
                self._max_event = event_epoch

    # -- the fold ---------------------------------------------------------

    def _union_rows(self, lo: Optional[int] = None,
                    hi: Optional[int] = None):
        """Concatenated raw rows of the committed epochs (in commit
        order) whose event epoch falls in ``[lo, hi)`` (all when
        unbounded). This union is the dataset a cold run must ingest
        for bit-identity."""
        parts = []
        with self._lock:
            for rec in self._epochs:
                e = rec["event_epoch"]
                if lo is not None and e < lo:
                    continue
                if hi is not None and e >= hi:
                    continue
                parts.append(self._epoch_rows[rec["epoch"]])
        if not parts:
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.float32) if self._has_value else None)
        pid = np.concatenate([p[0] for p in parts])
        pk = np.concatenate([p[1] for p in parts])
        value = (np.concatenate([p[2] for p in parts])
                 if parts[0][2] is not None else None)
        return pid, pk, value

    def _encode_wire(self, pid, pk, value):
        """The exact cold ingest: encode_rows under the fixed public
        vocabulary, then ingest_resident_wire with the pinned chunk
        schedule and the session's mesh bucket layout."""
        n_dev = self._mesh.devices.size if self._mesh is not None else 1
        if len(pid) == 0:
            return dataclasses.replace(
                streaming._empty_resident_wire(
                    max(len(self._pk_vocab), 1)), n_dev=n_dev)
        e_pid, e_pk, e_value, _, pk_vocab = encoding.encode_rows(
            encoding.ColumnarData(pid=pid, pk=pk, value=value), True,
            None, None, public_partitions=self._public,
            factorize_pid=False)
        self._pk_vocab = pk_vocab
        return streaming.ingest_resident_wire(
            e_pid, e_pk, e_value, num_partitions=max(len(pk_vocab), 1),
            n_chunks=self._live_n_chunks, n_dev=n_dev)

    def _micro_encode(self, pid, pk, value) -> None:
        """The pre-commit gate: re-drives the SlabDriver ingest schedule
        over JUST the new rows (same encoder, pinned chunk count). Rows
        that cannot encode fail here — before the WAL commit point — so
        a poisoned batch can never become a committed epoch the reopen
        fold would then choke on."""
        with obs_trace.span("serving/micro_encode", session=self._name,
                            n_rows=len(pid)):
            self._encode_wire(pid, pk, value)

    def _fold_union(self):
        with profiler.stage("dp/ingest"), \
                obs_trace.span("serving/fold", session=self._name,
                               epochs=len(self._epochs)):
            return self._encode_wire(*self._union_rows())

    def _sweep_stale_bound_entries(self, old_fp: str) -> None:
        """Epoch bump invalidation (caller holds self._lock): drops the
        full-wire bound entries keyed to the pre-fold fingerprint.
        Sealed-window entries carry a ("window", a, b, fp) prefix and
        survive — their wires are immutable once sealed."""
        stale = [k for k in self._bound_cache
                 if isinstance(k[0], tuple) and k[0][:1] == ("wire_fp",)
                 and k[0][1] == old_fp]
        for k in stale:
            self._cache_bytes -= self._bound_cache.pop(k).nbytes

    def _accumulate(self, k_kernel, *, mesh, resilience=None, **kw):
        # Full-session queries tag their bound entries with the live
        # wire's fingerprint: a fold invalidates exactly them.
        wire = self._wire
        return self._accumulate_wire(
            wire, ("wire_fp", wire.fingerprint), k_kernel, mesh=mesh,
            resilience=resilience, **kw)

    def _batch_key_prefix(self):
        # query_batch's planner keys must match _accumulate's cache
        # keys exactly, or batch-warmed entries would never hit (and a
        # fold's sweep would miss them).
        return ("wire_fp", self._wire.fingerprint)

    # -- window queries ---------------------------------------------------

    def window_wire(self, a: int, b: int):
        """The sealed window's ResidentWire — the union of its rows
        through the cold ingest (cached per window; immutable once
        sealed, which is why only sealed windows are queryable)."""
        if not self.is_sealed(a, b):
            raise ValueError(
                f"window [{a},{b}) is not sealed (sealed horizon "
                f"{self.sealed_horizon}): querying an open window would "
                f"give non-deterministic answers; append more data or "
                f"advance_watermark past {b + self._live_window.allowed_lateness}")
        key = (a, b)
        with self._lock:
            wire = self._window_wires.get(key)
        if wire is not None:
            return wire
        pid, pk, value = self._union_rows(a, b)
        wire = self._encode_wire(pid, pk, value)
        with self._lock:
            self._window_wires[key] = wire
        return wire

    def window_query(self, a: int, b: int, params, *,
                     epsilon: Optional[float] = None, delta: float = 0.0,
                     seed: int = 0, tenant: Optional[str] = None,
                     **query_kwargs):
        """One DP query over the sealed window ``[a, b)`` — bit-identical
        to the same query over the window's rows ingested cold with the
        session's pinned chunk count. Tenant charges carry the window's
        ledger tag, so per-window budget caps (register_tenant's
        window_epsilon/window_delta) apply."""
        view = _WindowView(self, self.window_wire(a, b), a, b)
        binding = _LiveBinding(view=view, window_tag=f"w[{a},{b})")
        return self.query(params, epsilon=epsilon, delta=delta, seed=seed,
                          tenant=tenant, _live=binding, **query_kwargs)

    # -- persistence ------------------------------------------------------

    def save(self, store=None) -> str:
        path = super().save(store)
        # super().save rebuilt the manifest from scratch; restore the
        # live section so open() keeps refusing and open_live keeps
        # finding the window configuration.
        store, name = self._store_binding
        store.record_live(name, self._live_meta())
        return path

    def release_schedule(self, schedule_id: str, params, *,
                         epsilon: float, delta: float = 0.0,
                         tenant: str, base_seed: int = 0,
                         empty_policy: str = "release",
                         **query_kwargs) -> "ReleaseSchedule":
        """A continual-release schedule over this session's sealed
        windows (see :class:`ReleaseSchedule`). Recreating it with the
        same ``schedule_id`` after a reopen reattaches its outcome WAL —
        recorded windows stay released, missed ones catch up on the
        next :meth:`~ReleaseSchedule.tick`."""
        return ReleaseSchedule(self, schedule_id, params,
                               epsilon=epsilon, delta=delta,
                               tenant=tenant, base_seed=base_seed,
                               empty_policy=empty_policy,
                               query_kwargs=query_kwargs)

    @classmethod
    def _reopen(cls, store, name: str, manifest: dict, *, mesh=None,
                resident_bytes=None, epilogue_cache=None,
                read_only: bool = False) -> "LiveDatasetSession":
        """SessionStore.open_live's worker: WAL replay -> digest-checked
        epoch payloads -> one union fold. Lands at exactly the epoch
        the WAL committed; the stored wire.npz (a point-in-time spill)
        is ignored — the WAL is authoritative.

        ``read_only=True`` builds a follower replica: the WAL is
        scanned with the truncation-free ``journal.read_records`` (a
        live primary may still be appending — the follower must never
        truncate its torn tail or open it for append), no WAL handle or
        audit binding is created, and the replica keeps a replication
        cursor for :meth:`apply_wal_payloads`."""
        live = manifest["live"]
        n_dev = mesh.devices.size if mesh is not None else 1
        if manifest["n_dev"] != n_dev:
            raise ValueError(
                f"session {name!r} was created for n_dev="
                f"{manifest['n_dev']}; opening with n_dev={n_dev} "
                f"cannot replay it (pass the matching mesh)")
        knobs = manifest["knobs"]
        public = manifest["public_partitions"]
        vocab = encoding.Vocabulary(list(public))
        self = cls._restore(
            dataclasses.replace(
                streaming._empty_resident_wire(max(len(vocab), 1)),
                n_dev=n_dev),
            vocab,
            public_partitions=public, mesh=mesh, name=manifest["name"],
            secure_host_noise=knobs["secure_host_noise"],
            segment_sort=knobs["segment_sort"],
            compact_merge=knobs["compact_merge"],
            resident_bytes=resident_bytes,
            epilogue_cache=epilogue_cache,
            store_binding=None if read_only else (store, name))
        self._init_live(WindowSpec.from_meta(live["window"]),
                        int(live["n_chunks"]), None)
        if read_only:
            # Late-bind the store WITHOUT _bind_audit (no append handle
            # on the primary's audit WAL) and without a _wal handle.
            self._store_binding = (store, name)
            self._read_only = True
            payloads = journal_lib.read_records(
                store.append_wal_path(name))
        else:
            self._wal = journal_lib.JsonlWal(store.append_wal_path(name))
            payloads = self._wal.recovered
        for payload in payloads:
            self._apply_wal_payload(payload, store, name)
        self._applied_wal_records = len(payloads)
        self._deadletters = set(store.deadletter_digests(name))
        self._next_epoch = len(self._epochs)
        self._wire = self._fold_union()
        self._folded_epochs = len(self._epochs)
        if (mesh is None and self._wire.n_rows > 0
                and self._wire.host_nbytes <= self._byte_budget):
            self._wire.ensure_device()
        return self

    def close(self) -> None:
        super().close()
        if self._wal is not None:
            self._wal.close()
            self._wal = None


def window_seed(base_seed: int, a: int, b: int) -> int:
    """The deterministic per-window seed of a ReleaseSchedule: derived
    from (base_seed, window bounds) alone, so catch-up after a crash
    re-derives the same seed — and the release journal can recognize a
    replay of the same window's token."""
    h = hashlib.sha256(f"{base_seed}:{a}:{b}".encode()).digest()
    return int.from_bytes(h[:4], "big") % (2 ** 31 - 1)


class ReleaseSchedule:
    """Continual DP releases over a live session's sealed windows,
    exactly once across restarts.

    Each :meth:`tick` answers every sealed-but-unrecorded window in
    order (one query per window, deterministic per-window seed) and
    records the outcome on the schedule's own fsync'd WAL — *after* the
    release, so a crash in between errs toward an unrecorded window
    whose catch-up re-run is refused by the tenant's at-most-once
    release journal (``DoubleReleaseError``) and recorded as
    ``"recovered"``; the charge is exactly refunded. Windows with no
    rows default to ``empty_policy="release"`` (a noise-only release
    over the public partitions — *suppressing* them would leak that the
    window was empty, which is data; "suppress" is available for
    pipelines whose emptiness is public knowledge).

    A deliberate :meth:`replay` of a recorded window surfaces the
    ``DoubleReleaseError`` to the caller — the refusal IS the contract.
    """

    def __init__(self, session: LiveDatasetSession, schedule_id: str,
                 params, *, epsilon: float, delta: float = 0.0,
                 tenant: str, base_seed: int = 0,
                 empty_policy: str = "release",
                 query_kwargs: Optional[dict] = None):
        if empty_policy not in ("release", "suppress"):
            raise ValueError(
                f"empty_policy must be 'release' or 'suppress', got "
                f"{empty_policy!r}")
        if tenant is None:
            raise ValueError(
                "a ReleaseSchedule needs a tenant: the tenant's "
                "at-most-once release journal is what refuses "
                "cross-restart replays, and its ledger carries the "
                "per-window budget")
        session._ensure_writable("release_schedule()")
        session.tenant(tenant)  # fail fast on unknown tenants
        store, name = session.store_binding
        self._session = session
        self._id = schedule_id
        self._params = params
        self._epsilon = epsilon
        self._delta = delta
        self._tenant = tenant
        self._base_seed = base_seed
        self._empty_policy = empty_policy
        self._query_kwargs = dict(query_kwargs or {})
        self._wal = journal_lib.JsonlWal(store.schedule_path(name,
                                                             schedule_id))
        # The schedule's outcome WAL is fenced like every other WAL of
        # a leased session: a superseded primary cannot record (or
        # sync) outcomes a successor now owns.
        fence = session._wal_fence()
        if fence is not None:
            self._wal.attach_fence(fence)
        self._recorded: Dict[tuple, str] = {}
        for payload in self._wal.recovered:
            self._recorded[(int(payload["a"]), int(payload["b"]))] = \
                payload["outcome"]

    @property
    def schedule_id(self) -> str:
        return self._id

    @property
    def recorded(self) -> Dict[tuple, str]:
        """{(a, b): outcome} of every recorded window."""
        return dict(self._recorded)

    def due_windows(self) -> List[tuple]:
        """Sealed windows with no recorded outcome, in order — what the
        next tick will answer (catch-up after a reopen included)."""
        return [w for w in self._session.sealed_windows()
                if w not in self._recorded]

    def tick(self) -> List[dict]:
        """Releases every due window; returns one record per window:
        {"window": (a, b), "outcome": "released" | "recovered" |
        "suppressed", "seed": int, "result": columns or None}.

        An admission shed / deadline / engine failure propagates with
        the window left unrecorded (its charge already exactly
        refunded by the query path) — the next tick retries it."""
        out = []
        for a, b in self.due_windows():
            t0 = time.perf_counter()
            wseed = window_seed(self._base_seed, a, b)
            with obs_trace.span("serving/release_tick",
                                session=self._session.name,
                                schedule=self._id, a=a, b=b):
                record = self._release_window(a, b, wseed)
            self._wal.append({"seq": self._wal.next_seq, "a": a, "b": b,
                              "outcome": record["outcome"],
                              "seed": wseed})
            self._recorded[(a, b)] = record["outcome"]
            obs_metrics.release_tick_seconds().observe(
                time.perf_counter() - t0)
            obs_flight.record("release_tick",
                              session=self._session.name,
                              schedule=self._id, a=a, b=b,
                              outcome=record["outcome"])
            out.append(record)
        return out

    def _release_window(self, a: int, b: int, wseed: int) -> dict:
        record = {"window": (a, b), "seed": wseed, "result": None}
        wire = self._session.window_wire(a, b)
        if wire.n_rows == 0 and self._empty_policy == "suppress":
            profiler.count_event(EVENT_RELEASES_SUPPRESSED)
            record["outcome"] = "suppressed"
            return record
        try:
            result = self._session.window_query(
                a, b, self._params, epsilon=self._epsilon,
                delta=self._delta, seed=wseed, tenant=self._tenant,
                **self._query_kwargs)
            record["result"] = result.to_columns()
            record["outcome"] = "released"
            profiler.count_event(EVENT_SCHEDULED_RELEASES)
            # The harness's crash seam between release and record:
            # reopen finds the window due, re-runs it, and the release
            # journal's refusal becomes outcome "recovered".
            _maybe_crash("release", a)
        except journal_lib.DoubleReleaseError:
            # The window's token committed before a crash wiped the
            # outcome record: the release already happened (or was
            # about to — the journal errs toward "never twice"), the
            # charge was exactly refunded by the query path. Record,
            # don't re-draw.
            record["outcome"] = "recovered"
            profiler.count_event(EVENT_RELEASES_RECOVERED)
        return record

    def replay(self, a: int, b: int):
        """Deliberately re-runs a recorded window — which the tenant's
        release journal refuses with DoubleReleaseError. Exists so
        operators (and tests) can PROVE the at-most-once property
        rather than trust it."""
        if (a, b) not in self._recorded:
            raise ValueError(
                f"window [{a},{b}) has no recorded outcome; nothing to "
                f"replay — tick() releases due windows")
        wseed = window_seed(self._base_seed, a, b)
        return self._session.window_query(
            a, b, self._params, epsilon=self._epsilon, delta=self._delta,
            seed=wseed, tenant=self._tenant, **self._query_kwargs)

    def close(self) -> None:
        self._wal.close()
