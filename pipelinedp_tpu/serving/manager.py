"""SessionManager: a fleet of resident datasets under one budget, with
admission control, per-query deadlines, and an LRU demotion ladder.

One ``DatasetSession`` is a dataset; a serving process holds many. This
module is the fleet layer (SERVING.md "Fleet operation"):

  * **Residency budget** — every admitted session's bytes (device copy,
    host slab, bound cache) count against ONE global budget. When an
    admit or re-hydration overflows it, least-recently-used sessions
    demote down the ladder: device-resident → host slab
    (``demote_device``) → disk spill (``spill`` through the
    ``SessionStore``) → on-demand re-hydration at their next query.
    Sessions with queries in flight are never demoted past their slab.
  * **Admission control** — a bounded in-flight gate: a query arriving
    while ``max_inflight`` queries are executing is *shed* with a typed
    :class:`SessionOverloadedError` (it never queues, so latency under
    overload is bounded by the gate, not by an unbounded backlog).
  * **Deadlines** — the manager's ``default_deadline_s`` (or
    ``PIPELINEDP_TPU_QUERY_DEADLINE_S``) rides every query of a managed
    session: the slab driver checks it between windows and the whole
    replay runs under a DispatchWatchdog, so even a wedged replay
    surfaces as a retryable ``QueryDeadlineError`` within the deadline.

The manager is thread-safe; its lock is never held while another
session's lifecycle lock is awaited *and* vice versa (sessions notify
the manager only after releasing their own lifecycle lock), so query
threads and demotion sweeps cannot deadlock.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Dict, List, Optional

from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import ops_plane as ops_plane_lib
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.serving import session as session_lib
from pipelinedp_tpu.serving import store as store_lib

# Tuning knobs (README "Tuning knobs" + SERVING.md):
#   PIPELINEDP_TPU_SERVING_INFLIGHT — max concurrently executing
#     queries across the fleet before shedding (default 8).
INFLIGHT_ENV = "PIPELINEDP_TPU_SERVING_INFLIGHT"

# Fleet profiler event counters (profiler.count_event / event_count):
EVENT_DEMOTIONS = "serving/sessions_demotions"
EVENT_SPILLS = "serving/sessions_spills"
EVENT_SHED = "serving/queries_shed"
# serving/sessions_rehydrations is credited by session.rehydrate
# (session_lib.EVENT_REHYDRATIONS) so un-managed rehydrations count too.


def max_inflight_default() -> int:
    """Validated PIPELINEDP_TPU_SERVING_INFLIGHT (default 8)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(INFLIGHT_ENV, 8, 1, 1 << 16)


class SessionOverloadedError(RuntimeError):
    """The in-flight query gate is full: this query is shed, not queued.

    Typed load shedding is the overload contract (SERVING.md): the
    caller retries with backoff or routes elsewhere; the serving
    process never accumulates an unbounded backlog behind a slow or
    wedged query."""

    def __init__(self, inflight: int, max_inflight: int):
        super().__init__(
            f"serving overloaded: {inflight} queries in flight (gate "
            f"{max_inflight}); query shed — retry with backoff")
        self.inflight = inflight
        self.max_inflight = max_inflight


def fleet_counters(manager: Optional["SessionManager"] = None
                   ) -> Dict[str, int]:
    """Snapshot of the fleet counters (bench.py surfaces this).
    ``sessions_resident``/``sessions_spilled`` are gauges of the given
    manager; the rest are process-wide monotonic counters."""
    out = {
        "demotions": profiler.event_count(EVENT_DEMOTIONS),
        "spills": profiler.event_count(EVENT_SPILLS),
        "rehydrations": profiler.event_count(
            session_lib.EVENT_REHYDRATIONS),
        "queries_shed": profiler.event_count(EVENT_SHED),
        "query_deadline_hits": profiler.event_count(
            session_lib.EVENT_DEADLINE_HITS),
        "device_fallbacks": profiler.event_count(
            session_lib.EVENT_DEVICE_FALLBACKS),
        "bound_cache_corrupt_dropped": profiler.event_count(
            store_lib.EVENT_BOUND_DROPPED),
    }
    from pipelinedp_tpu.serving import fleet as fleet_lib
    out["fleet"] = fleet_lib.fleet_counters()
    if manager is not None:
        with manager._lock:
            sessions = list(manager._sessions.values())
        out["sessions_resident"] = sum(1 for s in sessions
                                       if not s.is_spilled)
        out["sessions_spilled"] = sum(1 for s in sessions if s.is_spilled)
    return out


class SessionManager:
    """Admits DatasetSessions under one residency budget (module doc).

    store: the SessionStore backing the spill rung (and ``open``);
      defaults to ``SessionStore()`` (PIPELINEDP_TPU_SESSION_DIR).
    budget_bytes: the global residency budget across all admitted
      sessions; defaults to PIPELINEDP_TPU_RESIDENT_BYTES.
    max_inflight: the admission gate width
      (PIPELINEDP_TPU_SERVING_INFLIGHT).
    default_deadline_s: per-query deadline for managed sessions; None
      defers to PIPELINEDP_TPU_QUERY_DEADLINE_S (0 = none).
    ops_port: starts the observability endpoint (obs/ops_plane.py:
      /metrics, /healthz, /statusz, /debug/flightz) over this manager —
      0 binds an ephemeral port; None defers to
      PIPELINEDP_TPU_OPS_PORT (unset/0 = no endpoint). ``close()``
      stops it.
    """

    def __init__(self, store: Optional[store_lib.SessionStore] = None, *,
                 budget_bytes: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 ops_port: Optional[int] = None):
        self._store = store if store is not None else store_lib.SessionStore()
        self._budget = (int(budget_bytes) if budget_bytes is not None
                        else session_lib.resident_byte_budget())
        self._max_inflight = (int(max_inflight) if max_inflight is not None
                              else max_inflight_default())
        self.default_deadline_s = default_deadline_s
        self._lock = threading.Lock()
        self._inflight = 0
        # LRU order: least-recently-queried first.
        self._sessions: "collections.OrderedDict[str, session_lib.DatasetSession]"
        self._sessions = collections.OrderedDict()
        if ops_port is None:
            ops_port = ops_plane_lib.env_ops_port()
        self._ops_server = (ops_plane_lib.serve_ops(self, port=ops_port)
                            if ops_port is not None else None)

    @property
    def store(self) -> store_lib.SessionStore:
        return self._store

    @property
    def budget_bytes(self) -> int:
        return self._budget

    @property
    def max_inflight(self) -> int:
        return self._max_inflight

    @property
    def ops_server(self):
        """The running obs endpoint (ops_plane.OpsServer), or None."""
        return self._ops_server

    # -- membership ------------------------------------------------------

    def create(self, name: str, data, **session_kwargs
               ) -> session_lib.DatasetSession:
        """Ingests a new session and admits it under the fleet budget
        (kwargs go to DatasetSession; ``name`` is forced)."""
        session_kwargs["name"] = name
        session = session_lib.DatasetSession(data, **session_kwargs)
        return self.attach(session)

    def open(self, name: str, **open_kwargs) -> session_lib.DatasetSession:
        """Re-hydrates a stored session from the manager's store and
        admits it."""
        session = self._store.open(name, **open_kwargs)
        return self.attach(session)

    def open_live(self, name: str, **open_kwargs
                  ) -> session_lib.DatasetSession:
        """Reopens a stored LIVE session (append-WAL replay + union
        fold; serving/live.py) from the manager's store and admits it —
        its appends and scheduled releases then run under the fleet's
        admission gate and deadlines like any query."""
        session = self._store.open_live(name, **open_kwargs)
        return self.attach(session)

    def attach(self, session: session_lib.DatasetSession
               ) -> session_lib.DatasetSession:
        """Admits an existing session: it joins the LRU set, its queries
        route through the admission gate and default deadline, and its
        bytes count against the fleet budget (which may demote others
        right now)."""
        with self._lock:
            if session.name in self._sessions:
                raise ValueError(
                    f"a session named {session.name!r} is already "
                    f"admitted")
            session._manager = self
            self._sessions[session.name] = session
        self._enforce_budget(protect=session)
        return session

    def get(self, name: str) -> session_lib.DatasetSession:
        with self._lock:
            if name not in self._sessions:
                raise KeyError(f"no admitted session named {name!r}")
            return self._sessions[name]

    def remove(self, name: str) -> session_lib.DatasetSession:
        """Detaches a session from the fleet (does not close it)."""
        with self._lock:
            session = self._sessions.pop(name)
        session._manager = None
        return session

    def close(self) -> None:
        """Closes every admitted session and empties the fleet (and
        stops the obs endpoint when one is running)."""
        if self._ops_server is not None:
            self._ops_server.close()
            self._ops_server = None
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for session in sessions:
            session._manager = None
            session.close()

    # -- queries ---------------------------------------------------------

    def query(self, name: str, params, **query_kwargs):
        """Routes one query to an admitted session (re-hydrating it
        first when spilled); equivalent to ``get(name).query(...)``."""
        return self.get(name).query(params, **query_kwargs)

    def query_batch(self, name: str, configs, **batch_kwargs):
        """Routes a batch through the session's query planner
        (re-hydrating first when spilled); equivalent to
        ``get(name).query_batch(...)``. The whole batch rides one
        admission slot — shedding is all-or-nothing, matching the
        plan's all-or-nothing refund domain."""
        return self.get(name).query_batch(configs, **batch_kwargs)

    @contextlib.contextmanager
    def admission(self):
        """The bounded in-flight gate: entered by every query of a
        managed session. Full gate → typed shed, never a queue.

        The gate-acquisition wait (lock contention — sheds don't wait,
        by design) feeds the ``pipelinedp_tpu_admission_wait_seconds``
        histogram, and the in-flight count is exported as a gauge."""
        t0 = time.perf_counter()
        with self._lock:
            if self._inflight >= self._max_inflight:
                profiler.count_event(EVENT_SHED)
                # Admission decisions feed the flight recorder (via the
                # span-event hook, tracer or not): a post-mortem shows
                # the overload the process was shedding against.
                obs_trace.event("shed", inflight=self._inflight,
                                max_inflight=self._max_inflight)
                raise SessionOverloadedError(self._inflight,
                                             self._max_inflight)
            self._inflight += 1
            obs_metrics.inflight_queries().set(self._inflight)
        obs_metrics.admission_wait_seconds().observe(
            time.perf_counter() - t0)
        try:
            yield
        finally:
            with self._lock:
                self._inflight -= 1
                obs_metrics.inflight_queries().set(self._inflight)

    def notify_used(self, session, rehydrated: bool) -> None:
        """Called by a session at query start (after its lifecycle lock
        dropped): LRU-touch, and re-enforce the budget when the query
        just re-hydrated a spilled session."""
        with self._lock:
            if session.name in self._sessions:
                self._sessions.move_to_end(session.name)
        if rehydrated:
            self._enforce_budget(protect=session)

    # -- the demotion ladder ---------------------------------------------

    def resident_bytes(self) -> int:
        """Fleet-wide resident bytes (device + host slab + bound caches
        of every non-spilled admitted session)."""
        with self._lock:
            sessions = list(self._sessions.values())
        return sum(s.stats()["resident_bytes"] for s in sessions
                   if not s.is_spilled)

    def _enforce_budget(self, protect=None) -> None:
        """Demotes LRU sessions one rung at a time until the fleet fits
        the budget: device copy dropped first, then spill-to-store. The
        ``protect`` session (the one just admitted or re-hydrated) and
        sessions with queries in flight are skipped — at worst the
        fleet transiently overshoots by the active working set, it
        never thrashes the session being served."""
        while True:
            resident = self.resident_bytes()
            obs_metrics.fleet_resident_bytes().set(resident)
            if resident <= self._budget:
                return
            with self._lock:
                candidates = [s for s in self._sessions.values()
                              if s is not protect and not s.is_spilled]
            demoted = False
            for candidate in candidates:  # LRU first
                with obs_trace.span("fleet/demote",
                                    session=candidate.name):
                    if candidate.demote_device():
                        profiler.count_event(EVENT_DEMOTIONS)
                        obs_trace.event("demote_device",
                                        session=candidate.name)
                        demoted = True
                        break
                    if candidate.spill(self._store):
                        profiler.count_event(EVENT_DEMOTIONS)
                        profiler.count_event(EVENT_SPILLS)
                        obs_trace.event("spill", session=candidate.name)
                        demoted = True
                        break
            if not demoted:
                return  # nothing left to demote; overshoot transiently

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            names = list(self._sessions)
            inflight = self._inflight
        per_session = {}
        for name in names:
            try:
                per_session[name] = self.get(name).stats()
            except KeyError:
                continue
        return {
            "budget_bytes": self._budget,
            "resident_bytes": self.resident_bytes(),
            "max_inflight": self._max_inflight,
            "inflight": inflight,
            "default_deadline_s": self.default_deadline_s,
            "ops_url": (self._ops_server.url
                        if self._ops_server is not None else None),
            "sessions": per_session,
        }
