"""Fleet failover tier: leased single-writer sessions, hot followers,
and exactly-once releases across host death.

PR 8/10/15 made one *process* crash-exactly-once against its own store
directory; this module extends the contract to a *fleet* sharing that
directory (ROADMAP item 1, SERVING.md "Fleet failover"). Three pieces:

  * :class:`SessionLease` — a fencing-token lease file per session
    directory. Acquisition is an atomic claim (``O_CREAT|O_EXCL`` claim
    file resolves races) followed by a tmp+fsync+rename publish of the
    new lease record; every renew is the same atomic publish, so a
    crash mid-renew leaves the previous valid lease, never a torn one.
    The monotonically increasing ``token`` is the fence: sessions
    attach ``lease.admit`` to their WALs
    (:meth:`runtime.journal.JsonlWal.attach_fence`), so *every* append
    re-checks the on-disk lease and embeds the token in the record — a
    partitioned-away ex-primary whose lease was taken over is refused
    at the journal (:class:`runtime.journal.StaleWriterError`), not
    merely raced.

  * :class:`FollowerSession` — a hot read-only replica. It opens the
    primary's session ``read_only=True`` (no lease, no WAL handles —
    the read path never truncates or appends the primary's files; see
    :func:`runtime.journal.read_records`) and polls the append WAL,
    digest-verifying each committed epoch payload against its WAL
    record before folding it into the replica's ``ResidentWire``. Warm
    read-only queries are served off that replayed wire;
    ``replication_lag`` (records behind + poll age) is surfaced on
    ``/statusz`` and ``/fleetz``.

  * :class:`FleetRouter` — steers queries across hosts: deterministic
    pid-shard ownership picks the owner, an unhealthy owner is shed
    *across* hosts before any within-host shedding triggers, and when
    a query's deadline budget is nearly burnt the router hedges warm
    (tenantless) reads to a follower instead of betting the remaining
    budget on the primary.

Failover is follower-driven: when the primary's lease expires (host
death — the pid-liveness probe only helps same-host restarts),
:meth:`FollowerSession.promote` closes the replica and reopens the
session *writable* — acquiring the lease, truncating any torn WAL
tail (``JsonlWal`` recovery), and replaying ``ReleaseSchedule``
catch-up. Exactly-once releases across the failover need nothing new:
the durable release journal + ledger already refuse a release the dead
primary committed (``DoubleReleaseError`` → "recovered" outcome with
the charge refunded exactly), and an uncommitted one re-issues
bit-identically under the same ``window_seed``. The two-process kill
harness (tests/kill_harness.py ``fleet_*`` modes) pins the whole
story: SIGKILL the primary mid-release, promote the follower, and the
released stream byte-compares against an uninterrupted single-host
run, with the fenced ex-primary's late append refused.
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import socket
import tempfile
import time
from typing import Dict, List, Optional

from pipelinedp_tpu import profiler
from pipelinedp_tpu.runtime import journal as journal_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import watchdog as watchdog_lib

# Validated env knobs (README "Tuning knobs", SERVING.md):
#   PIPELINEDP_TPU_LEASE_TTL_S — single-writer lease TTL in seconds. A
#     primary renews at half-TTL; a follower may promote once the lease
#     is this stale. Smaller = faster failover, more renew I/O.
#   PIPELINEDP_TPU_FOLLOWER_POLL_MS — hot-follower WAL poll period.
LEASE_TTL_ENV = "PIPELINEDP_TPU_LEASE_TTL_S"
FOLLOWER_POLL_ENV = "PIPELINEDP_TPU_FOLLOWER_POLL_MS"

LEASE_FILE = "lease.json"

# Profiler event counters (profiler.count_event / event_count):
EVENT_LEASE_RENEWALS = "serving/fleet_lease_renewals"
EVENT_LEASE_TAKEOVERS = "serving/fleet_lease_takeovers"
EVENT_FENCED_WRITES = "serving/fleet_fenced_writes"
EVENT_PROMOTIONS = "serving/fleet_promotions"
EVENT_FOLLOWER_POLLS = "serving/fleet_follower_polls"
EVENT_FOLLOWER_RECORDS = "serving/fleet_follower_records"
EVENT_HEDGED_READS = "serving/fleet_hedged_reads"
EVENT_HEDGED_HITS = "serving/fleet_hedged_hits"
EVENT_CROSS_HOST_SHEDS = "serving/fleet_cross_host_sheds"

# Re-exported so fleet callers catch one typed error for "your lease is
# gone" whether it surfaces from the lease API or from a fenced WAL.
StaleWriterError = journal_lib.StaleWriterError


class LeaseHeldError(RuntimeError):
    """The session's single-writer lease is validly held elsewhere —
    opening writable would create the dual-primary split this module
    exists to prevent. Open ``read_only=True`` (a follower) or wait for
    expiry/release."""


class LeaseLostError(StaleWriterError):
    """This process's lease is no longer the one on disk (taken over,
    released, or removed): every fenced write path must stop — a newer
    primary owns the session now."""


def lease_ttl_s() -> float:
    """The PIPELINEDP_TPU_LEASE_TTL_S default (seconds)."""
    from pipelinedp_tpu.native import loader
    return float(loader.env_int(LEASE_TTL_ENV, 30, 1, 3600))


def follower_poll_s() -> float:
    """The PIPELINEDP_TPU_FOLLOWER_POLL_MS default, in seconds."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(FOLLOWER_POLL_ENV, 100, 1, 60000) / 1000.0


def _pid_alive(pid: int) -> bool:
    """Best-effort same-host liveness probe (signal 0). PermissionError
    means the pid exists under another uid — alive."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return True  # unknown — err toward "alive" (no takeover)
    return True


def read_lease(path: str) -> Optional[dict]:
    """The on-disk lease record, or None when absent/unreadable.

    Unreadable is treated like absent on purpose: lease writes are
    tmp+fsync+rename, so a torn record cannot exist — garbage here
    means the file never was a lease, and refusing forever would wedge
    the session with no holder to fix it."""
    try:
        with open(path, "rb") as f:
            record = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(record, dict) or \
            not isinstance(record.get("token"), int):
        return None
    return record


def _write_lease(path: str, record: dict) -> None:
    """Atomic, durable lease publish: tmp + fsync + rename (DPL012) —
    a crash mid-write leaves the previous lease intact, and the new
    record's bytes are on disk before the rename makes it visible."""
    parent = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(json.dumps(record, sort_keys=True).encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class SessionLease:
    """One process's hold on a session directory's single-writer lease.

    Use :meth:`acquire`; the constructor only wires fields (tests forge
    stale leases with it). The instance is owned by one session/thread;
    the cross-process protocol lives entirely in the lease file:

      * ``token`` — strictly increasing across takeovers; THE fence.
      * ``pid``/``host`` — the holder, for liveness probes and ops.
      * ``expires_unix`` — wall clock, because two hosts cannot share a
        monotonic clock. The in-process renewal *pacing* still rides a
        monotonic :class:`watchdog.Deadline` so a wall-clock jump never
        convinces a healthy primary it already expired.
      * ``released`` — a clean close handed the lease back; the next
        acquire may take over immediately.
    """

    def __init__(self, path: str, *, token: int, ttl_s: float,
                 pid: Optional[int] = None, host: Optional[str] = None,
                 clock=time.time):
        self.path = path
        self.token = int(token)
        self.ttl_s = float(ttl_s)
        self.pid = os.getpid() if pid is None else pid
        self.host = socket.gethostname() if host is None else host
        self._clock = clock
        self._released = False
        self._renewals = 0
        self._deadline = watchdog_lib.Deadline.after(self.ttl_s)

    # -- acquisition ------------------------------------------------------

    @classmethod
    def acquire(cls, path: str, *, ttl_s: Optional[float] = None,
                force: bool = False, clock=time.time) -> "SessionLease":
        """Acquires (or takes over) the lease at ``path``.

        Takeover is allowed only when the current record is absent,
        released, expired, held by this same pid+host (re-entrant —
        an in-process reopen of one's own session), or held by a
        *dead* pid on this host (liveness probe; a SIGKILL'd primary's
        successor must not wait out a long TTL). ``force=True`` skips
        eligibility — operator surgery only. A validly-held lease
        raises :class:`LeaseHeldError`.

        Races between eligible claimants are resolved by an
        ``O_CREAT|O_EXCL`` claim file named after the *next* token:
        both see token T and want T+1, exactly one creates
        ``lease.json.claim.<T+1>``; the loser raises LeaseHeldError and
        may retry (by then the winner's record is visible). A claim
        file orphaned by a crash older than the TTL is swept.
        """
        if ttl_s is None:
            ttl_s = lease_ttl_s()
        ttl_s = float(ttl_s)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        current = read_lease(path)
        takeover = current is not None and not current.get("released")
        if not force and not cls._eligible(current, clock):
            raise LeaseHeldError(
                f"{path}: lease token {current['token']} is held by "
                f"pid {current.get('pid')}@{current.get('host')} for "
                f"another {current.get('expires_unix', 0) - clock():.1f}s"
                f" — open read_only=True (follower) or wait for "
                f"expiry/release")
        token = (current["token"] + 1) if current is not None else 1
        claim = f"{path}.claim.{token}"
        try:
            os.close(os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             0o644))
        except OSError as exc:
            if exc.errno != errno.EEXIST:
                raise
            # A crashed claimant's orphan blocks this token forever;
            # sweep it once it is TTL-stale, else lose the race.
            try:
                stale = clock() - os.stat(claim).st_mtime > ttl_s
            except OSError:
                stale = False
            if not stale:
                raise LeaseHeldError(
                    f"{path}: lost the takeover race for token {token}")
            try:
                os.unlink(claim)
            except OSError:
                pass
            return cls.acquire(path, ttl_s=ttl_s, force=force,
                               clock=clock)
        try:
            latest = read_lease(path)
            latest_token = latest["token"] if latest is not None else None
            current_token = (current["token"] if current is not None
                             else None)
            if latest_token != current_token:
                raise LeaseHeldError(
                    f"{path}: lease changed hands (token "
                    f"{current_token!r} -> {latest_token!r}) while "
                    f"claiming token {token}")
            lease = cls(path, token=token, ttl_s=ttl_s, clock=clock)
            lease._publish()
        finally:
            try:
                os.unlink(claim)
            except OSError:
                pass
        if takeover:
            profiler.count_event(EVENT_LEASE_TAKEOVERS)
        return lease

    @staticmethod
    def _eligible(current: Optional[dict], clock) -> bool:
        if current is None or current.get("released"):
            return True
        if clock() > float(current.get("expires_unix", 0.0)):
            return True
        host = socket.gethostname()
        if current.get("host") == host:
            if current.get("pid") == os.getpid():
                return True  # re-entrant: our own prior open
            if not _pid_alive(int(current.get("pid", -1))):
                return True  # dead same-host holder (SIGKILL'd primary)
        return False

    # -- holder operations ------------------------------------------------

    def _record(self) -> dict:
        now = self._clock()
        return {"token": self.token, "pid": self.pid, "host": self.host,
                "ttl_s": self.ttl_s, "renewed_unix": now,
                "expires_unix": now + self.ttl_s,
                "released": self._released}

    def _publish(self) -> None:
        _write_lease(self.path, self._record())
        self._deadline = watchdog_lib.Deadline.after(self.ttl_s)

    def renew(self) -> None:
        """Extends the expiry by one TTL (atomic publish). Raises
        :class:`LeaseLostError` when the on-disk token is no longer
        ours — the session was taken over; every fenced write path is
        already refusing, and so must the renewer."""
        self._check_held()
        self._publish()
        self._renewals += 1
        profiler.count_event(EVENT_LEASE_RENEWALS)

    def renew_with_retry(self,
                         policy: Optional[retry_lib.RetryPolicy] = None
                         ) -> None:
        """Renewal with bounded decorrelated-jitter backoff on
        filesystem hiccups (a fleet renewing against one shared store
        must not thundering-herd; the jitter seed is the token, so
        chaos runs reproduce). LeaseLostError is never retried — a
        newer token on disk is a fact, not a fault."""
        if policy is None:
            policy = retry_lib.RetryPolicy(jitter="decorrelated",
                                           jitter_seed=self.token)
        for attempt in range(policy.max_retries + 1):
            try:
                self.renew()
                policy.reset_backoff()
                return
            except LeaseLostError:
                raise
            except OSError:
                if attempt >= policy.max_retries:
                    raise
                policy.sleep(policy.backoff_s(attempt))

    def maintain(self) -> bool:
        """Renews once the in-process expiry deadline drops below half
        (``Deadline.fraction_remaining`` — the same monotonic pacing
        the router's hedging uses). Call from the primary's work loop;
        returns True when a renewal happened."""
        if self._deadline.fraction_remaining() >= 0.5:
            return False
        self.renew_with_retry()
        return True

    def admit(self) -> int:
        """The WAL fence (JsonlWal.attach_fence): re-reads the on-disk
        lease on *every* append and returns the token to embed, or
        raises :class:`LeaseLostError` when the token on disk is not
        ours (taken over / released / removed). Mere TTL expiry with
        our token still on disk is admitted: the fence's job is
        refusing writes that would race a *successor*, and until a
        successor claims a new token there is nobody to race."""
        if self._released:
            profiler.count_event(EVENT_FENCED_WRITES)
            raise LeaseLostError(
                f"{self.path}: lease token {self.token} was released by "
                f"this process; the session is closed for writes")
        current = read_lease(self.path)
        if current is None or current["token"] != self.token \
                or current.get("released"):
            profiler.count_event(EVENT_FENCED_WRITES)
            disk = current["token"] if current is not None else None
            raise LeaseLostError(
                f"{self.path}: write fenced — this process holds lease "
                f"token {self.token} but disk shows {disk!r}; a newer "
                f"primary owns the session (stale-writer append "
                f"refused)")
        return self.token

    def release(self) -> None:
        """Hands the lease back (marks the record released so the next
        acquire may take over immediately). Idempotent; a lease we no
        longer hold is left alone — it is the successor's now."""
        if self._released:
            return
        self._released = True
        current = read_lease(self.path)
        if current is not None and current["token"] == self.token:
            _write_lease(self.path, self._record())

    def _check_held(self) -> None:
        if self._released:
            raise LeaseLostError(
                f"{self.path}: lease token {self.token} was released")
        current = read_lease(self.path)
        if current is None or current["token"] != self.token \
                or current.get("released"):
            disk = current["token"] if current is not None else None
            raise LeaseLostError(
                f"{self.path}: lease token {self.token} superseded by "
                f"{disk!r} on disk")

    # -- introspection ----------------------------------------------------

    @property
    def released(self) -> bool:
        return self._released

    def status(self) -> dict:
        """Lease fields for /statusz and /fleetz."""
        current = read_lease(self.path)
        return {
            "token": self.token,
            "pid": self.pid,
            "host": self.host,
            "ttl_s": self.ttl_s,
            "renewals": self._renewals,
            "released": self._released,
            "held": (current is not None
                     and current["token"] == self.token
                     and not current.get("released")),
            "expires_in_s": (
                None if current is None
                else round(float(current.get("expires_unix", 0.0))
                           - self._clock(), 3)),
        }


class FollowerSession:
    """A hot, digest-verified read-only replica of a live session.

    Opens the session ``read_only=True`` (no lease, no WAL file
    handles) and tails the primary's append WAL with the truncation-
    free :func:`runtime.journal.read_records` scanner. Every new
    ``append`` record's epoch payload is loaded through
    ``SessionStore.load_epoch`` — which refuses any payload failing the
    content digest the WAL record committed — before folding into the
    replica's wire, so a follower can never serve bits the primary
    never acknowledged. Tenants are deliberately NOT replicated: budget
    ledgers and release journals are single-writer state owned by the
    lease holder; followers serve warm *tenantless* reads only.
    """

    def __init__(self, store, name: str, *, mesh=None,
                 poll_s: Optional[float] = None):
        self._store = store
        self._name = name
        self._mesh = mesh
        self._poll_s = follower_poll_s() if poll_s is None else \
            float(poll_s)
        self._last_poll_unix: Optional[float] = None
        self._promoted = False
        self._session = store.open_live(name, mesh=mesh, read_only=True)

    @property
    def session(self):
        """The read-only replica session (serves warm queries)."""
        return self._session

    @property
    def name(self) -> str:
        return self._name

    @property
    def poll_s(self) -> float:
        return self._poll_s

    def poll(self) -> int:
        """One replication step: applies every append-WAL record beyond
        what the replica has folded. Returns the number applied."""
        profiler.count_event(EVENT_FOLLOWER_POLLS)
        self._last_poll_unix = time.time()
        payloads = journal_lib.read_records(
            self._store.append_wal_path(self._name))
        applied = self._session.applied_wal_records
        fresh = payloads[applied:]
        if fresh:
            self._session.apply_wal_payloads(fresh)
            profiler.count_event(EVENT_FOLLOWER_RECORDS, len(fresh))
        return len(fresh)

    def replication_lag(self) -> dict:
        """(records_behind, poll age) without mutating the replica —
        the /statusz+/fleetz lag surface."""
        payloads = journal_lib.read_records(
            self._store.append_wal_path(self._name))
        behind = len(payloads) - self._session.applied_wal_records
        return {
            "records_behind": max(0, behind),
            "poll_age_s": (None if self._last_poll_unix is None else
                           round(time.time() - self._last_poll_unix, 3)),
            "poll_s": self._poll_s,
        }

    def lease_status(self) -> Optional[dict]:
        """The primary's lease record as seen from this follower (the
        promotion decision input)."""
        return read_lease(os.path.join(self._store.path(self._name),
                                       LEASE_FILE))

    def primary_dead(self) -> bool:
        """True when nobody validly holds the lease: expired, released,
        absent, or a dead same-host pid — i.e. promotion is eligible.
        (Delegates to the acquire eligibility rules, so the follower
        never *thinks* it can promote and then finds it cannot.)"""
        return SessionLease._eligible(self.lease_status(), time.time)

    def promote(self, *, ttl_s: Optional[float] = None, force: bool = False):
        """Failover: close the replica and reopen the session WRITABLE.

        The writable open acquires the lease (new fencing token — the
        dead primary's late writes are refused from this instant),
        truncates any torn WAL tail (JsonlWal recovery; the torn record
        was never acknowledged), and replays the full epoch log;
        ``ReleaseSchedule.replay`` then refuses already-committed
        releases (exact refund) and re-issues uncommitted windows
        bit-identically under the same window_seed. Returns the new
        primary session; this follower is consumed."""
        if self._promoted:
            raise RuntimeError(f"follower of {self._name!r} was already "
                               f"promoted")
        self._session.close()
        primary = self._store.open_live(
            self._name, mesh=self._mesh,
            lease_ttl_s=ttl_s, force_lease=force)
        self._promoted = True
        profiler.count_event(EVENT_PROMOTIONS)
        return primary

    def statusz(self) -> dict:
        lease = self.lease_status()
        return {
            "name": self._name,
            "role": "follower",
            "promoted": self._promoted,
            "replication": self.replication_lag(),
            "primary_lease": lease,
            "primary_dead": self.primary_dead(),
        }

    def close(self) -> None:
        if not self._promoted:
            self._session.close()


class FleetRouter:
    """Steers queries across a fleet of hosts serving shared sessions.

    Hosts register a query target (a ``DatasetSession``-shaped object:
    ``query(params, **kw)`` + ``stats()``); followers register for
    hedged warm reads. Routing is three rules, in order:

      1. **ownership** — partition shards map deterministically onto
         the sorted host ring (sha256 of the shard key, mod n), so
         every router instance agrees without coordination;
      2. **shed across before within** — an unhealthy owner (health
         override, else a live probe of ``stats()``) is skipped and
         the query walks the ring; likewise a
         ``SessionOverloadedError`` from one host tries the next host
         before surfacing, so one hot host sheds to the fleet before
         clients see backpressure;
      3. **hedge near the deadline** — a warm (tenantless) read whose
         ``Deadline.fraction_remaining()`` has burnt past the hedge
         threshold is answered by a follower replica instead of
         betting the last of the budget on the primary (tenant queries
         never hedge: budget/ledger state is single-writer).
    """

    def __init__(self, *, hedge_fraction: float = 0.25):
        if not 0.0 <= hedge_fraction <= 1.0:
            raise ValueError(f"hedge_fraction must be in [0, 1], got "
                             f"{hedge_fraction}")
        self._hedge_fraction = float(hedge_fraction)
        self._hosts: Dict[str, object] = {}
        self._health: Dict[str, Optional[bool]] = {}
        self._followers: List[FollowerSession] = []

    # -- membership -------------------------------------------------------

    def add_host(self, host_id: str, target) -> None:
        if host_id in self._hosts:
            raise ValueError(f"host {host_id!r} already registered")
        self._hosts[host_id] = target
        self._health[host_id] = None

    def remove_host(self, host_id: str) -> None:
        self._hosts.pop(host_id, None)
        self._health.pop(host_id, None)

    def add_follower(self, follower: FollowerSession) -> None:
        self._followers.append(follower)

    def set_health(self, host_id: str, healthy: Optional[bool]) -> None:
        """Operator/health-checker override; ``None`` returns the host
        to live probing."""
        if host_id not in self._hosts:
            raise ValueError(f"unknown host {host_id!r}")
        self._health[host_id] = healthy

    def healthy(self, host_id: str) -> bool:
        override = self._health.get(host_id)
        if override is not None:
            return override
        target = self._hosts.get(host_id)
        if target is None:
            return False
        try:
            target.stats()  # the /healthz probe: answers == healthy
        except Exception:
            return False
        return True

    # -- routing ----------------------------------------------------------

    def owner_of(self, shard_key) -> str:
        """The owning host for a partition shard: stable sha256 ring
        placement, identical on every router."""
        if not self._hosts:
            raise RuntimeError("FleetRouter has no hosts")
        ring = sorted(self._hosts)
        digest = hashlib.sha256(repr(shard_key).encode()).digest()
        return ring[int.from_bytes(digest[:8], "big") % len(ring)]

    def _candidates(self, shard_key) -> List[str]:
        ring = sorted(self._hosts)
        start = ring.index(self.owner_of(shard_key))
        return ring[start:] + ring[:start]

    def query(self, params, *, shard_key=0, deadline=None, tenant=None,
              **kwargs):
        """Routes one query (kwargs thread into ``target.query``).

        ``deadline`` is an optional :class:`watchdog.Deadline`; when
        its remaining fraction drops below the hedge threshold and the
        query is tenantless, a follower replica answers instead."""
        from pipelinedp_tpu.serving.manager import SessionOverloadedError
        if deadline is not None and tenant is None and self._followers \
                and deadline.fraction_remaining() < self._hedge_fraction:
            profiler.count_event(EVENT_HEDGED_READS)
            for follower in self._followers:
                try:
                    result = follower.session.query(params, **kwargs)
                except Exception:
                    continue
                profiler.count_event(EVENT_HEDGED_HITS)
                return result
            # every follower refused — fall through to the primaries
        candidates = [h for h in self._candidates(shard_key)
                      if self.healthy(h)]
        if not candidates:
            raise RuntimeError("FleetRouter: no healthy hosts")
        owner = self.owner_of(shard_key)
        last_overload = None
        for host_id in candidates:
            if host_id != owner:
                # shedding ACROSS hosts (owner unhealthy or overloaded)
                # before any within-host admission queueing kicks in.
                profiler.count_event(EVENT_CROSS_HOST_SHEDS)
            try:
                return self._hosts[host_id].query(
                    params, tenant=tenant, **kwargs)
            except SessionOverloadedError as exc:
                last_overload = exc
                continue
        raise last_overload

    def statusz(self) -> dict:
        return {
            "hosts": {h: {"healthy": self.healthy(h),
                          "override": self._health.get(h)}
                      for h in sorted(self._hosts)},
            "followers": [f.statusz() for f in self._followers],
            "hedge_fraction": self._hedge_fraction,
        }


def fleet_counters() -> dict:
    """The fleet tier's profiler counters (obs surface; see also
    serving.manager.fleet_counters which merges these with the
    admission/store counters)."""
    return {
        "lease_renewals": profiler.event_count(EVENT_LEASE_RENEWALS),
        "lease_takeovers": profiler.event_count(EVENT_LEASE_TAKEOVERS),
        "fenced_writes": profiler.event_count(EVENT_FENCED_WRITES),
        "promotions": profiler.event_count(EVENT_PROMOTIONS),
        "follower_polls": profiler.event_count(EVENT_FOLLOWER_POLLS),
        "follower_records": profiler.event_count(EVENT_FOLLOWER_RECORDS),
        "hedged_reads": profiler.event_count(EVENT_HEDGED_READS),
        "hedged_hits": profiler.event_count(EVENT_HEDGED_HITS),
        "cross_host_sheds": profiler.event_count(EVENT_CROSS_HOST_SHEDS),
    }
