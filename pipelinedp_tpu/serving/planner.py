"""Query planner for the serving batch path (SERVING.md "Query plane").

`DatasetSession.query_batch` compiles its configs through this module
BEFORE any launch: the planner decides, as pure data, which configs skip
replay entirely (their resolved-sampler bound key is already cached),
which configs share one replay lane (identical bound keys dedupe to a
single vmapped lane), and how the surviving lanes fuse into launch
groups (configs whose kernel statics agree ride one batched launch).
Budget, release-journal, and audit state never enter the plan — each
config keeps its own; the plan only routes accumulator work.

Everything here is deliberately free of session/device state so plans
are unit-testable as plain objects: the session supplies hashable bound
keys and fusion keys, the planner returns index routing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Hashable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class PlanEntry:
    """One query config as the planner sees it.

    bound_key: the config's resolved-sampler accumulator-cache key (the
    exact key `_accumulate_wire` would use), or None when the config is
    not cacheable; fusion_key: the kernel statics the batched replay is
    specialized on — configs must share it to share a launch;
    need_flags: the config's own accumulator-column needs (used to union
    flags per group and to gate cache inserts on exact-column parity).
    """
    index: int
    bound_key: Optional[Hashable]
    fusion_key: Hashable
    need_flags: Tuple[bool, bool, bool, bool]
    cached: bool = False


@dataclasses.dataclass(frozen=True)
class ReplayLane:
    """One vmapped lane of a launch group: the owner index's config
    parameterizes the lane; follower indexes had an identical bound key
    and reuse the lane's accumulators without replaying."""
    owner: int
    followers: Tuple[int, ...] = ()

    @property
    def indexes(self) -> Tuple[int, ...]:
        return (self.owner,) + self.followers


@dataclasses.dataclass(frozen=True)
class LaunchGroup:
    """One batched replay launch: len(lanes) <= max_width lanes sharing
    one set of kernel statics. union_flags is the OR of every member
    config's need_flags (the launch computes the union of columns;
    per-config finalize reads only its own). flags_exact[i] marks lanes
    whose own need_flags equal the union — only those lanes' results may
    populate the bound cache, since a solo replay of that config would
    have produced exactly these columns."""
    fusion_key: Hashable
    union_flags: Tuple[bool, bool, bool, bool]
    lanes: Tuple[ReplayLane, ...]
    flags_exact: Tuple[bool, ...]


@dataclasses.dataclass(frozen=True)
class QueryPlan:
    """The compiled batch: cache-skipped config indexes go straight to
    finalize; launch groups replay in order. stats feed the session's
    planner counters."""
    groups: Tuple[LaunchGroup, ...]
    cached_indexes: Tuple[int, ...]
    stats: Dict[str, int]

    @property
    def n_lanes(self) -> int:
        return sum(len(g.lanes) for g in self.groups)


def _union(flags: Sequence[Tuple[bool, bool, bool, bool]]
           ) -> Tuple[bool, bool, bool, bool]:
    return (any(f[0] for f in flags), any(f[1] for f in flags),
            any(f[2] for f in flags), any(f[3] for f in flags))


def compile_plan(entries: Sequence[PlanEntry],
                 max_width: int) -> QueryPlan:
    """Compiles a batch of entries into a QueryPlan.

    Three passes, all pure:
      1. admission — entries flagged `cached` skip replay entirely;
      2. dedupe — identical bound keys collapse to one lane (the first
         occurrence owns the lane; later ones follow it), so duplicate
         configs replay the wire exactly once;
      3. fusion — lanes group by fusion_key and split at max_width; each
         group's launch computes the union of its members' need_flags.

    Entries with bound_key=None never dedupe (each owns a private lane).
    """
    if max_width < 1:
        raise ValueError(f"max_width must be >= 1, got {max_width}")
    cached: List[int] = []
    lane_of: Dict[Hashable, int] = {}
    lanes: List[List[int]] = []        # member entry positions
    lane_entries: List[PlanEntry] = []  # owner entry per lane
    dedupes = 0
    by_pos = {e.index: e for e in entries}
    if len(by_pos) != len(entries):
        raise ValueError("duplicate entry indexes in batch plan")
    for e in entries:
        if e.cached:
            cached.append(e.index)
            continue
        if e.bound_key is not None and e.bound_key in lane_of:
            lanes[lane_of[e.bound_key]].append(e.index)
            dedupes += 1
            continue
        if e.bound_key is not None:
            lane_of[e.bound_key] = len(lanes)
        lanes.append([e.index])
        lane_entries.append(e)
    # Fusion: preserve first-seen order of fusion keys, then split wide
    # groups at max_width (matching the pre-planner launch splitting).
    fused: Dict[Hashable, List[int]] = {}
    for lane_idx, owner in enumerate(lane_entries):
        fused.setdefault(owner.fusion_key, []).append(lane_idx)
    groups: List[LaunchGroup] = []
    for fusion_key, lane_idxs in fused.items():
        for s in range(0, len(lane_idxs), max_width):
            chunk = lane_idxs[s:s + max_width]
            member_flags = []
            for li in chunk:
                member_flags.extend(by_pos[i].need_flags
                                    for i in lanes[li])
            union_flags = _union(member_flags)
            group_lanes = tuple(
                ReplayLane(owner=lanes[li][0],
                           followers=tuple(lanes[li][1:]))
                for li in chunk)
            flags_exact = tuple(
                lane_entries[li].bound_key is not None
                and lane_entries[li].need_flags == union_flags
                for li in chunk)
            groups.append(LaunchGroup(
                fusion_key=fusion_key, union_flags=union_flags,
                lanes=group_lanes, flags_exact=flags_exact))
    stats = {
        "configs": len(entries),
        "cache_skips": len(cached),
        "dedupes": dedupes,
        "lanes": len(lane_entries),
        "fused_groups": len(groups),
    }
    return QueryPlan(groups=tuple(groups), cached_indexes=tuple(cached),
                     stats=stats)
