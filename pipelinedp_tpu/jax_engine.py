"""JaxDPEngine: the TPU-native columnar execution engine.

Same public contract as DPEngine.aggregate (params, extractors, budget
accounting, explain reports, lazy results) but executes the whole
aggregation as fused jitted kernels over columnar arrays instead of per-row
dataflow: dictionary-encode keys on host, one fused bound-and-aggregate
kernel (sort + segment reductions), one vectorized partition-selection call,
and one batched noise call per mechanism (SURVEY.md §7 architecture stance).

Budget-accounting parity is structural: the engine builds the exact same
CompoundCombiner as DPEngine (same request_budget calls in the same order,
combiners.py:849-922) and then *reads the mechanism specs off the
combiners* to parameterize the device kernels — so (eps, delta) splits are
identical to the reference path by construction.

The lazy-budget contract survives jit: noise scales/granularities enter the
kernels as runtime scalars, computed from the resolved specs at execution
time (after compute_budgets), so recompilation never depends on budgets.
"""

from __future__ import annotations

import enum
import functools
import math
import time
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import combiners as combiners_lib
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import report_generator as report_generator_lib
from pipelinedp_tpu.aggregate_params import (
    AggregateParams, CalculatePrivateContributionBoundsParams, MechanismType,
    Metrics, NoiseKind, NormKind, PrivateContributionBounds,
    SelectPartitionsParams)
from pipelinedp_tpu import dp_engine as dp_engine_lib
from pipelinedp_tpu.data_extractors import DataExtractors
from pipelinedp_tpu.ops import columnar, encoding, noise as noise_ops
from pipelinedp_tpu.ops import finalize as finalize_ops
from pipelinedp_tpu.ops import streaming
from pipelinedp_tpu.ops import wirecodec
from pipelinedp_tpu.ops import quantiles as quantile_ops
from pipelinedp_tpu.ops import selection as selection_ops
from pipelinedp_tpu import quantile_tree as quantile_tree_lib
from pipelinedp_tpu import partition_selection as ps_lib
from pipelinedp_tpu.report_generator import ExplainComputationReport
from pipelinedp_tpu import noise_core
from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import trace as obs_trace


def _mechanism_noise_params(spec: budget_accounting.MechanismSpec,
                            sensitivities: dp_computations.Sensitivities):
    """(is_gaussian, scale_or_std, granularity) runtime scalars for a spec."""
    mech = dp_computations.create_additive_mechanism(spec, sensitivities)
    if mech.noise_kind == NoiseKind.GAUSSIAN:
        return True, mech.std, noise_core.gaussian_granularity(mech.std)
    return False, mech.noise_parameter, noise_core.laplace_granularity(
        mech.noise_parameter)


def derive_contribution_caps(params: AggregateParams, compound, n_rows: int,
                             num_partitions: int):
    """(linf_cap, l0_cap, l1_cap) for the bounding kernels.

    The single derivation of the engine's contribution-bound caps from
    the aggregation params + compound combiner (parity:
    DPEngine._create_contribution_bounder, dp_engine.py:285-400), shared
    by the standard aggregate path, the custom-combiner path, and the
    serving layer's batched resident queries — so a batched config's caps
    can never drift from what its sequential run would use.
    """
    if (compound.expects_per_partition_sampling()
            and params.max_contributions_per_partition):
        linf_cap = params.max_contributions_per_partition
    else:
        linf_cap = max(n_rows, 1)
    l0_cap = (params.max_partitions_contributed
              if params.max_partitions_contributed else num_partitions)
    if not params.perform_cross_partition_contribution_bounding:
        # Linf-only bounding (utility-analysis mode): noise stays
        # calibrated to the declared L0 bound, but no partitions drop.
        l0_cap = num_partitions
    l1_cap = None
    if params.max_contributions is not None:
        # L1 bounding: a uniform sample of max_contributions rows per
        # privacy unit across all partitions; Linf/L0 caps disabled.
        l1_cap = params.max_contributions
        linf_cap = max(n_rows, 1)
        l0_cap = num_partitions
    if params.contribution_bounds_already_enforced:
        # The input already satisfies the bounds; apply none.
        linf_cap = max(n_rows, 1)
        l0_cap = num_partitions
    return linf_cap, l0_cap, l1_cap


def derive_need_flags(compound) -> Tuple[bool, bool, bool, bool]:
    """(need_count, need_sum, need_norm, need_norm_sq) — which accumulator
    columns the compound's combiners actually read. Dropped columns save
    two full-HBM segment passes each in the kernel. Shared by _execute
    and the serving layer's batched resident queries."""
    return (
        any(isinstance(c, (combiners_lib.CountCombiner,
                           combiners_lib.MeanCombiner,
                           combiners_lib.VarianceCombiner))
            for c in compound.combiners),
        any(isinstance(c, combiners_lib.SumCombiner)
            for c in compound.combiners),
        any(isinstance(c, (combiners_lib.MeanCombiner,
                           combiners_lib.VarianceCombiner))
            for c in compound.combiners),
        any(isinstance(c, combiners_lib.VarianceCombiner)
            for c in compound.combiners),
    )


def derive_clip_bounds(params: AggregateParams):
    """(row_lo, row_hi, group_lo, group_hi, middle) for the bounding
    kernels, from the params' bounds mode. Shared by _execute and the
    serving layer's batched resident queries."""
    if params.bounds_per_partition_are_set:
        row_lo, row_hi = -np.inf, np.inf
        glo, ghi = (params.min_sum_per_partition,
                    params.max_sum_per_partition)
    elif params.bounds_per_contribution_are_set:
        row_lo, row_hi = params.min_value, params.max_value
        glo, ghi = -np.inf, np.inf
    else:
        row_lo, row_hi = -np.inf, np.inf
        glo, ghi = -np.inf, np.inf
    middle = (dp_computations.compute_middle(params.min_value,
                                             params.max_value)
              if params.bounds_per_contribution_are_set else 0.0)
    return row_lo, row_hi, glo, ghi, middle


class KeyTag(enum.IntEnum):
    """Reserved ``fold_in`` tags for the engine's PRNG substreams.

    Combiner substreams use the combiner index (0..n_combiners-1);
    QUANTILE_NOISE sits far above any realistic combiner count so the
    quantile tree's per-level noise stream can never collide with them.
    """
    QUANTILE_NOISE = 10_000


class KeyStream:
    """The audited PRNG-key source for the engine (dplint DPL001's blessed
    idiom: every key is derived exactly once and never reused).

    Two disciplines live here so key management has a single reviewed
    surface instead of ad-hoc ``fold_in`` call sites:

      * ``next_key()`` — a monotone counter folded into the root key; each
        engine-level operation (aggregate / select_partitions /
        add_dp_noise) draws one distinct key. Reproduces the historical
        ``fold_in(root_key, counter)`` sequence bit-for-bit, so seeded
        device-mode runs are unchanged across the refactor.
      * ``derive(key, tag)`` — substream derivation under a named tag
        (``KeyTag`` member or a loop index), replacing magic integers in
        ``fold_in`` calls. Deriving never consumes: the parent key remains
        valid for further ``derive`` calls with distinct tags.
    """

    def __init__(self, root_key):
        self._root_key = root_key
        self._counter = 0

    def next_key(self):
        """A fresh key, never handed out before."""
        self._counter += 1
        return jax.random.fold_in(self._root_key, self._counter)

    @property
    def counter(self) -> int:
        """How many keys have been handed out. Checkpoints and release
        tokens record this position so a resumed run under a different
        key schedule is refused (runtime/checkpoint.py)."""
        return self._counter

    def fingerprint(self) -> str:
        """Digest of the root key. (root fingerprint, counter) names the
        KeyStream state exactly — it is the release-token identity of
        runtime/journal.py, derived without consuming any key."""
        from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib
        return checkpoint_lib.key_fingerprint(self._root_key)

    @staticmethod
    def derive(key, tag):
        """A substream of ``key`` under ``tag`` (see KeyTag)."""
        return jax.random.fold_in(key, int(tag))


class _LazyColumns:
    """Deferred column-dict result: computes on first access — after
    BudgetAccountant.compute_budgets(), per the lazy-budget contract
    (accessing unresolved specs raises)."""

    def __init__(self, compute_fn):
        self._compute_fn = compute_fn
        self._columns = None

    def to_columns(self) -> dict:
        """Returns {'partition_id', 'keep_mask', value arrays...}."""
        if self._columns is None:
            self._columns = self._compute_fn()
        return self._columns


class LazyJaxResult(_LazyColumns):
    """Deferred result of a columnar aggregation."""

    def __init__(self, compute_fn, pk_vocab: encoding.Vocabulary):
        super().__init__(compute_fn)
        self._pk_vocab = pk_vocab

    def to_columns(self) -> dict:
        """Returns {'partition_id', 'keep_mask', metric arrays...}
        ([num_partitions] arrays).

        Metric values of partitions dropped by partition selection are
        masked to NaN, so consuming the columns directly cannot leak
        non-kept partitions (keep_mask says which rows are real output).
        """
        return super().to_columns()

    def partition_keys(self) -> List[Any]:
        """Keys of the partitions present in the DP output (selection
        applied — non-kept partitions must not leak)."""
        cols = self.to_columns()
        keep = np.asarray(cols["keep_mask"])
        ids = np.asarray(cols["partition_id"])[keep]
        return self._pk_vocab.decode_all(ids)

    def __iter__(self):
        cols = self.to_columns()
        keep = np.asarray(cols["keep_mask"])
        kept_idx = np.flatnonzero(keep)
        # One batched vocabulary decode + one tolist per column instead of
        # a per-row decode/float() host loop.
        keys = self._pk_vocab.decode_all(
            np.asarray(cols["partition_id"])[kept_idx])
        metric_names = [
            name for name in cols
            if name not in ("partition_id", "keep_mask")
        ]
        kept_columns = []
        for name in metric_names:
            arr = np.asarray(cols[name])[kept_idx]
            kept_columns.append(arr.tolist() if arr.ndim == 1 else list(arr))
        tuple_type = combiners_lib._get_or_create_named_tuple(
            "MetricsTuple", tuple(metric_names))
        for key, *metrics in zip(keys, *kept_columns):
            yield (key, tuple_type(*metrics))


class _LazySelectedPartitions(_LazyColumns):
    """Deferred result of select_partitions: iterates kept partition keys."""

    def __init__(self, compute_fn, pk_vocab: encoding.Vocabulary):
        super().__init__(compute_fn)
        self._pk_vocab = pk_vocab

    def __iter__(self):
        cols = self.to_columns()
        ids = cols["partition_id"][cols["keep_mask"]]
        yield from self._pk_vocab.decode_all(ids)


class _LazyNoisedValues(_LazyColumns):
    """Deferred result of add_dp_noise: iterates (pk, noised value)."""

    def __init__(self, compute_fn, pk_col):
        super().__init__(compute_fn)
        self._pk_col = pk_col

    def __iter__(self):
        # Materialize both columns once (batched tolist gives native
        # Python scalars) instead of one .item()/float() per row.
        values = np.asarray(self.to_columns()["value"]).tolist()
        pk_col = self._pk_col
        if isinstance(pk_col, np.ndarray):
            pk_col = pk_col.tolist()
        yield from zip(pk_col, values)


class _LazyCustomResult(_LazyColumns):
    """Deferred result of a custom-combiner aggregation: iterates
    (partition_key, (metrics...)) like DPEngine's custom path."""

    def __init__(self, compute_fn, pk_vocab: encoding.Vocabulary):
        super().__init__(compute_fn)
        self._pk_vocab = pk_vocab

    def __iter__(self):
        cols = self.to_columns()
        for pk_id, metrics in zip(cols["partition_id"], cols["metrics"]):
            yield self._pk_vocab.decode(int(pk_id)), metrics


class JaxDPEngine:
    """Columnar DP engine. API parity with DPEngine for the aggregation
    surface; input may be Python rows (encoded on host) or pre-encoded
    columns.

    secure_host_noise: when True (default), the heavy bound-and-aggregate
    stage runs on device but the released noise (and thresholding/selection
    draws) are finalized on host in float64 with the full granularity
    snapping of noise_core — the Mironov-2012 mitigation float32 cannot
    provide (see ops/noise.py). The host step is O(num_partitions), off the
    hot path. Set False to keep everything on device (fastest; noise is
    distributionally correct but without bit-level guarantees).

    seed controls the device kernels: contribution-bounding sampling, and
    noise/selection in device mode. In secure_host_noise mode the released
    noise comes from the host secure sampler, which is deliberately NOT
    seedable through the engine (secure noise must not be replayable —
    same stance as the reference's PyDP path); tests can reseed the
    fallback RNGs via noise_core.seed_fallback_rng / partition_selection
    .seed_rng.

    mesh: a jax.sharding.Mesh with ('dp', 'mp') axes (see
    parallel.sharded.make_mesh). When set, the fused bound-and-aggregate
    kernel runs shard_map'ed over all mesh devices: rows are hash-sharded
    by privacy id on host (so contribution bounding needs no cross-device
    exchange), per-partition partials ride an ICI reduce-scatter, and the
    resulting accumulators stay sharded over the partition dimension — so
    selection and noise also run distributed under XLA's SPMD partitioner.
    Every metric and selection strategy works identically on any mesh; this
    is the framework's replacement for the reference's Beam/Spark cluster
    execution (pipeline_backend.py:223-474).
    """

    def __init__(self,
                 budget_accountant: budget_accounting.BudgetAccountant,
                 seed: int = 0,
                 secure_host_noise: bool = True,
                 mesh=None,
                 stream_chunks: Optional[int] = None,
                 value_transfer_dtype=None,
                 transfer_encoding: str = "auto",
                 compact_merge="auto",
                 segment_sort="auto",
                 fused_epilogue: bool = True,
                 epilogue_cache: Optional[finalize_ops.EpilogueCache] = None,
                 checkpoint_policy=None,
                 retry_policy=None,
                 release_journal=None,
                 fault_injector=None,
                 watchdog_timeout_s=None):
        self._budget_accountant = budget_accountant
        self._report_generators = []
        self._key_stream = KeyStream(jax.random.PRNGKey(seed))
        self._secure_host_noise = secure_host_noise
        self._mesh = mesh
        # The fused finalization epilogue (ops/finalize.py): one compiled
        # executable (device noise) or one batched host pass (secure host
        # noise) instead of a per-combiner op/sync loop. False restores
        # the legacy loop — kept as the parity oracle for tests.
        self._fused_epilogue = fused_epilogue
        # Executable cache shared across engines by default, so repeated
        # queries with the same shape hit warm epilogues with zero
        # retraces even from fresh engine instances.
        self._epilogue_cache = (epilogue_cache if epilogue_cache is not None
                                else finalize_ops.default_cache())
        # Streaming execution: large single-device inputs are hash-sharded
        # by privacy id into pid-disjoint chunks so the host->device
        # transfer overlaps the kernel (ops/streaming.py). stream_chunks=1
        # forces the single-shot path; None = auto.
        self._stream_chunks = stream_chunks
        # np.float16 halves the value-column transfer (lossy ingest,
        # opt-in; see ops/streaming.py).
        self._value_transfer_dtype = value_transfer_dtype
        # "auto": the lossless RLE/bit-plane wire codec (ops/wirecodec.py);
        # "bytes": the legacy fixed-width byte packing. Both exact.
        self._transfer_encoding = transfer_encoding
        # Compact chunk merge (ops/streaming.py): streamed chunks emit
        # compact per-group subtotal columns and ONE final merge scatters
        # them into the dense accumulators, instead of every chunk
        # re-paying the full [num_partitions] partition passes. "auto"
        # engages at >= streaming.COMPACT_MIN_PARTITIONS partitions (the
        # regime where those passes dominate); True forces it; False
        # restores the legacy per-chunk scatters (the parity oracle).
        self._compact_merge = compact_merge
        # Group-stage strategy of the streamed chunk kernels
        # (ops/columnar samplers; wirecodec.plan_group_binning resolves
        # the knob into a 4-way general/packed/tiled/hash dispatch):
        #   "auto"  — hash-binned SORTLESS group stage when it is
        #             provably bit-identical (columnar.hash_exact_gate +
        #             no norm columns), else the bucketed segment-local
        #             tiled sort when the tile heuristic wins, else the
        #             packed global sort. Bit-identical released values
        #             across all of these by construction.
        #   "hash"  — force the sortless group stage whenever its grid
        #             geometry is computable (chunks that overflow the
        #             bins demote to tiled per chunk): zero sort passes;
        #             exact counts always, sums ULP-close outside the
        #             exactness gate (bit-identical inside it).
        #   True    — force tiling whenever geometry permits.
        #   False   — the full round-8 kernel (global packed sort, f32
        #             payload, float accumulation — the parity oracle).
        self._segment_sort = segment_sort
        # Resilience knobs (pipelinedp_tpu/runtime/, RESILIENCE.md):
        #   checkpoint_policy: runtime.CheckpointPolicy — snapshot the
        #     streamed slab loop after each slab and auto-resume from the
        #     policy's store; a resumed run is bit-identical to an
        #     uninterrupted seeded run.
        #   retry_policy: runtime.RetryPolicy — bounded backoff for
        #     transient transfer/kernel failures; RESOURCE_EXHAUSTED
        #     halves the slab budget and re-issues (same per-chunk keys,
        #     so released values are unchanged).
        #   release_journal: runtime.ReleaseJournal — at-most-once noise
        #     release: a run that would re-draw already-released noise
        #     raises DoubleReleaseError instead.
        #   fault_injector: runtime.FaultInjector — deterministic fault
        #     scripting for tests (never set in production).
        #   watchdog_timeout_s: bounded timeouts around device transfer/
        #     dispatch in the streamed slab loop — a wedged operation
        #     surfaces as a retryable runtime.DispatchHangError within
        #     the timeout instead of hanging forever. None defers to
        #     PIPELINEDP_TPU_WATCHDOG_S (0 = disabled, the default).
        self._checkpoint_policy = checkpoint_policy
        self._retry_policy = retry_policy
        self._release_journal = release_journal
        self._fault_injector = fault_injector
        self._watchdog_timeout_s = watchdog_timeout_s

    def _next_key(self):
        return self._key_stream.next_key()

    # -- report plumbing (shared shape with DPEngine) -----------------------

    @property
    def _current_report_generator(self):
        return self._report_generators[-1]

    def _add_report_stage(self, stage):
        self._current_report_generator.add_stage(stage)

    def explain_computations_report(self):
        return [g.report() for g in self._report_generators]

    # -- aggregate ----------------------------------------------------------

    def aggregate(self,
                  col,
                  params: AggregateParams,
                  data_extractors: Optional[DataExtractors] = None,
                  public_partitions: Optional[Sequence[Any]] = None,
                  out_explain_computation_report: Optional[
                      ExplainComputationReport] = None) -> LazyJaxResult:
        is_columnar = (isinstance(
            col, (encoding.ColumnarData, encoding.EncodedColumns))
            or getattr(col, "is_resident_dataset", False))
        dp_engine_lib.DPEngine._check_aggregate_params(
            self, col, params, data_extractors,
            check_data_extractors=not is_columnar)
        dp_engine_lib.DPEngine._check_budget_accountant_compatibility(
            self, public_partitions is not None, params.metrics,
            params.custom_combiners is not None)
        self._check_supported(params)
        with self._budget_accountant.scope(weight=params.budget_weight):
            self._report_generators.append(
                report_generator_lib.ReportGenerator(
                    params, "aggregate", public_partitions is not None))
            if out_explain_computation_report is not None:
                out_explain_computation_report._set_report_generator(
                    self._current_report_generator)
            result = self._aggregate(col, params, data_extractors,
                                     public_partitions)
            self._budget_accountant._compute_budget_for_aggregation(
                params.budget_weight)
            return result

    # -- select_partitions / add_dp_noise (columnar fast paths) -------------

    def select_partitions(self,
                          col,
                          params: SelectPartitionsParams,
                          data_extractors: Optional[DataExtractors] = None):
        """DP-selected partition keys, computed on device.

        Columnar twin of DPEngine.select_partitions (dp_engine.py:170): one
        fused kernel L0-bounds each privacy unit's distinct partitions and
        counts distinct units per partition; one vectorized selection call
        decides the keys. Returns a lazy iterable of kept partition keys.
        """
        is_columnar = isinstance(
            col, (encoding.ColumnarData, encoding.EncodedColumns))
        if not is_columnar:
            dp_engine_lib.DPEngine._check_select_private_partitions(
                self, col, params, data_extractors)
        dp_engine_lib.DPEngine._check_budget_accountant_compatibility(
            self, False, [], False)
        with self._budget_accountant.scope(weight=params.budget_weight):
            self._report_generators.append(
                report_generator_lib.ReportGenerator(params,
                                                     "select_partitions",
                                                     False))
            spec = self._budget_accountant.request_budget(
                mechanism_type=MechanismType.GENERIC)
            pid_extractor = (None
                             if params.contribution_bounds_already_enforced
                             else (data_extractors.privacy_id_extractor
                                   if data_extractors is not None else True))
            pid, pk, _, _, pk_vocab = encoding.encode_rows(
                col,
                pid_extractor,
                data_extractors.partition_extractor
                if data_extractors else None,
                None,
                factorize_pid=False)
            num_partitions = max(len(pk_vocab), 1)
            l0 = params.max_partitions_contributed
            self._add_report_stage(
                f"Cross-partition contribution bounding: for each privacy_id "
                f"randomly select max(actual_partition_contributed, {l0}) "
                f"partitions")
            self._add_report_stage(
                lambda: f"Private partition selection: using "
                        f"{params.partition_selection_strategy.value} "
                        f"method with (eps={spec.eps}, delta={spec.delta})")
            key = self._next_key()
            key_counter = self._key_stream.counter
            engine = self

            def compute():
                engine._commit_release(key_counter, kind="selection_release")
                k_kernel, k_select = jax.random.split(key)
                counts = columnar.count_distinct_pids_per_partition(
                    jnp.asarray(pid), jnp.asarray(pk),
                    jnp.ones(len(pid), dtype=bool), k_kernel, l0,
                    num_partitions=num_partitions)
                exists = counts > 0
                strategy = ps_lib.create_partition_selection_strategy(
                    params.partition_selection_strategy, spec.eps,
                    spec.delta, l0, params.pre_threshold)
                keep, _ = engine._apply_selection(k_select, counts, exists,
                                                  strategy)
                return {
                    "partition_id":
                        np.arange(num_partitions, dtype=np.int32),
                    "keep_mask": np.asarray(keep),
                }

            result = _LazySelectedPartitions(compute, pk_vocab)
            self._budget_accountant._compute_budget_for_aggregation(
                params.budget_weight)
            return result

    def add_dp_noise(self,
                     col,
                     params,
                     out_explain_computation_report: Optional[
                         ExplainComputationReport] = None):
        """Adds calibrated DP noise to pre-aggregated (pk, value) pairs.

        Columnar twin of DPEngine.add_dp_noise (dp_engine.py:449): one
        batched noise call over the whole value column. Does NOT enforce
        sensitivity — the caller guarantees the declared l0/linf bounds
        hold and that the partition keys are public or DP-selected. Input
        is an iterable of (pk, value) pairs or a ColumnarData with
        pk/value set.
        """
        mechanism_type = params.noise_kind.convert_to_mechanism_type()
        spec = self._budget_accountant.request_budget(mechanism_type)
        sensitivities = dp_computations.Sensitivities(
            l0=params.l0_sensitivity, linf=params.linf_sensitivity)
        self._report_generators.append(
            report_generator_lib.ReportGenerator(params, "add_dp_noise",
                                                 True))
        if out_explain_computation_report is not None:
            out_explain_computation_report._set_report_generator(
                self._current_report_generator)

        if isinstance(col, encoding.ColumnarData):
            pk_col = np.asarray(col.pk)
            values = np.asarray(col.value, dtype=np.float64)
        else:
            pairs = list(col)
            pk_col = encoding._column_from_list([p for p, _ in pairs])
            values = np.array([v for _, v in pairs], dtype=np.float64)

        self._add_report_stage(
            lambda: (f"Adding {dp_computations.create_additive_mechanism(spec, sensitivities).noise_kind} "
                     f"noise with parameter "
                     f"{dp_computations.create_additive_mechanism(spec, sensitivities).noise_parameter}"))
        key = self._next_key()
        key_counter = self._key_stream.counter
        engine = self

        def compute():
            engine._commit_release(key_counter)
            is_g, scale, gran = _mechanism_noise_params(spec, sensitivities)
            # numpy in: the secure host path keeps float64 end to end; the
            # device path converts on entry.
            noised = engine._add_noise(key, values, is_g, scale, gran)
            return {
                "partition_id": np.arange(len(pk_col), dtype=np.int32),
                "keep_mask": np.ones(len(pk_col), dtype=bool),
                "value": np.asarray(noised),
            }

        result = _LazyNoisedValues(compute, pk_col)
        self._budget_accountant._compute_budget_for_aggregation(
            params.budget_weight)
        return result

    def calculate_private_contribution_bounds(
            self,
            col,
            params: CalculatePrivateContributionBoundsParams,
            data_extractors: Optional[DataExtractors] = None,
            partitions: Optional[Sequence[Any]] = None,
            partitions_already_filtered: bool = False
    ) -> PrivateContributionBounds:
        """DP choice of max_partitions_contributed via the exponential
        mechanism over dataset histograms, on the columnar path.

        Columnar twin of DPEngine.calculate_private_contribution_bounds
        (dp_engine.py:384; reference pipeline_dp/dp_engine.py:450-549):
        the L0 contribution histogram comes from the vectorized columnar
        histogram fast path (dataset_histograms/computing_histograms
        .compute_dataset_histograms_columnar) instead of a per-row
        pipeline, and the exponential-mechanism draw uses the same secure
        uniform sampler as the host engine. Supported for COUNT /
        PRIVACY_ID_COUNT aggregations.

        col: ColumnarData / EncodedColumns, or row iterable with
          data_extractors.
        partitions: the partition keys the aggregation will use (public or
          DP-selected). Required unless partitions_already_filtered and the
          number of partitions is taken from the filtered data itself.

        Returns the PrivateContributionBounds dataclass directly (the
        columnar engine has no deferred backend collections to wrap it in;
        DPEngine returns a 1-element collection with the same payload).
        """
        from pipelinedp_tpu.dataset_histograms import computing_histograms
        from pipelinedp_tpu import private_contribution_bounds as pcb_lib

        is_columnar = isinstance(
            col, (encoding.ColumnarData, encoding.EncodedColumns))
        dp_engine_lib.DPEngine.\
            _check_calculate_private_contribution_bounds_params(
                self, col, params, data_extractors,
                check_data_extractors=not is_columnar)

        if is_columnar:
            pid = np.asarray(col.pid)
            pk = np.asarray(col.pk)
        else:
            rows = list(col)
            pid = encoding._column_from_list(
                [data_extractors.privacy_id_extractor(r) for r in rows])
            pk = encoding._column_from_list(
                [data_extractors.partition_extractor(r) for r in rows])

        if partitions is not None:
            partitions = list(partitions)
            # Count the partitions from the USER-PROVIDED list before any
            # vocabulary translation: the exponential-mechanism scoring
            # must see every public partition, including keys with no data
            # (DPEngine parity — translating first silently dropped
            # unknown keys and deflated number_of_partitions).
            number_of_partitions = len(
                np.unique(encoding._column_from_list(partitions)))
            if (isinstance(col, encoding.EncodedColumns)
                    and col.pk_keys is not None):
                # EncodedColumns pk are dense ids; `partitions` arrives as
                # user-facing keys — translate through the vocabulary so
                # the filter compares ids to ids (keys absent from the
                # vocabulary cannot match any data row).
                id_of_key = {k: i for i, k in enumerate(col.pk_keys)}
                partitions = [id_of_key[p] for p in partitions
                              if p in id_of_key]
            partition_keys = np.unique(
                encoding._column_from_list(partitions))
            if not partitions_already_filtered:
                mask = np.isin(pk, partition_keys)
                pid, pk = pid[mask], pk[mask]
        elif partitions_already_filtered:
            number_of_partitions = len(np.unique(pk))
        else:
            raise ValueError(
                "partitions must be provided unless "
                "partitions_already_filtered=True")

        histograms = computing_histograms.compute_dataset_histograms_columnar(
            encoding.ColumnarData(pid=pid, pk=pk, value=None))
        scoring = pcb_lib.L0ScoringFunction(params, number_of_partitions,
                                            histograms.l0_contributions_histogram)
        candidates = pcb_lib.generate_possible_contribution_bounds(
            scoring.max_partitions_contributed_best_upper_bound())
        bound = dp_computations.ExponentialMechanism(scoring).apply(
            params.calculation_eps, candidates)
        return PrivateContributionBounds(max_partitions_contributed=bound)

    def _check_supported(self, params: AggregateParams):
        if any(m.is_percentile for m in params.metrics or []):
            if Metrics.VECTOR_SUM in params.metrics:
                raise NotImplementedError(
                    "PERCENTILE cannot be combined with VECTOR_SUM: the "
                    "quantile tree needs scalar values.")
            if params.min_value is None or params.max_value is None:
                raise ValueError(
                    "PERCENTILE requires min_value and max_value (the "
                    "quantile tree range).")
            if params.min_value >= params.max_value:
                # A zero-width tree range would produce NaN leaf indices
                # on device; fail loudly like the host quantile tree does.
                raise ValueError(
                    "PERCENTILE requires min_value < max_value (got "
                    f"[{params.min_value}, {params.max_value}]).")

    def _aggregate(self, col, params, data_extractors, public_partitions):
        resident = (col if getattr(col, "is_resident_dataset", False)
                    else None)
        if params.custom_combiners:
            if resident is not None:
                raise NotImplementedError(
                    "custom combiners are not supported on resident "
                    "dataset sessions (host combiner logic needs the raw "
                    "rows the session no longer holds)")
            return self._aggregate_custom(col, params, data_extractors,
                                          public_partitions)
        # Same budget requests as the reference graph.
        compound = combiners_lib.create_compound_combiner(
            params, self._budget_accountant)
        is_vector = Metrics.VECTOR_SUM in params.metrics
        selection_spec = None
        if (public_partitions is None and
                not params.post_aggregation_thresholding):
            selection_spec = self._budget_accountant.request_budget(
                mechanism_type=MechanismType.GENERIC)

        if resident is not None:
            # Resident-dataset fast path (pipelinedp_tpu/serving/): the
            # encode + sort + transfer phases were paid at ingest; the
            # session hands back the retained wire and the partition
            # vocabulary it was built with.
            if is_vector:
                raise NotImplementedError(
                    "VECTOR_SUM is not supported on resident dataset "
                    "sessions (the vector path has no wire codec to "
                    "retain)")
            if params.contribution_bounds_already_enforced:
                raise NotImplementedError(
                    "contribution_bounds_already_enforced re-interprets "
                    "every row as its own privacy unit, which changes the "
                    "wire; ingest the dataset that way instead")
            resident._check_engine_compat(self, public_partitions)
            pid = pk = value = None
            pk_vocab = resident.pk_vocab
            n_rows = resident.n_rows
        else:
            # Host-side columnar encoding (the extract + public-filter
            # stages). With contribution_bounds_already_enforced each row
            # is its own privacy unit and no bounding is applied (parity:
            # dp_engine.py:122). Columnar inputs carry their own pid
            # column; any non-None marker tells encode_rows to use it.
            pid_extractor = (data_extractors.privacy_id_extractor
                             if data_extractors is not None else True)
            if params.contribution_bounds_already_enforced:
                pid_extractor = None  # a unique id per row
            with profiler.stage("dp/encode"):
                pid, pk, value, pid_vocab, pk_vocab = encoding.encode_rows(
                    col,
                    pid_extractor,
                    data_extractors.partition_extractor
                    if data_extractors else None,
                    data_extractors.value_extractor
                    if data_extractors else None,
                    public_partitions=public_partitions,
                    vector_size=params.vector_size if is_vector else None,
                    factorize_pid=False)
            n_rows = len(pid)
        num_partitions = max(len(pk_vocab), 1)

        # When no child combiner expects per-partition sampling (e.g. the
        # per-partition-sum clipping mode), Linf bounding is the combiner's
        # job — disable the sampler (parity:
        # DPEngine._create_contribution_bounder, dp_engine.py:380-400).
        linf_cap, l0_cap, l1_cap = derive_contribution_caps(
            params, compound, n_rows, num_partitions)
        if params.contribution_bounds_already_enforced:
            self._add_report_stage(
                "Contribution bounding: skipped (already enforced by the "
                "caller)")
        elif l1_cap is not None:
            self._add_report_stage(
                f"Total contribution bounding: for each privacy_id randomly "
                f"select max(actual_contributions, {l1_cap}) contributions "
                f"across all partitions")
        else:
            self._add_report_stage(
                f"Per-partition contribution bounding: for each privacy_id "
                f"and each partition, randomly select max(actual_"
                f"contributions_per_partition, {linf_cap}) contributions.")
            if params.perform_cross_partition_contribution_bounding:
                self._add_report_stage(
                    f"Cross-partition contribution bounding: for each "
                    f"privacy_id randomly select max(actual_partition_"
                    f"contributed, {l0_cap}) partitions")
            else:
                self._add_report_stage(
                    "Cross-partition contribution bounding: skipped "
                    "(perform_cross_partition_contribution_bounding=False)")
        for stage in compound.explain_computation():
            self._add_report_stage(stage)

        kernel_key = self._next_key()
        key_counter = self._key_stream.counter
        engine = self

        def compute():
            with profiler.stage("dp/execute"):
                return engine._execute(compound, params, selection_spec,
                                       kernel_key, pid, pk, value,
                                       num_partitions, linf_cap, l0_cap,
                                       public_partitions is not None,
                                       is_vector, l1_cap=l1_cap,
                                       key_counter=key_counter,
                                       resident=resident)

        return LazyJaxResult(compute, pk_vocab)

    def _aggregate_custom(self, col, params: AggregateParams,
                          data_extractors, public_partitions):
        """Custom-combiner escape hatch (parity:
        create_compound_combiner_with_custom_combiners, reference
        combiners.py:925).

        Contribution bounding runs on the device — the fused kernel's row
        mask (columnar.bound_row_mask), identical sampling to the standard
        metrics path — and the user's combiner logic (arbitrary Python)
        runs on host over the surviving rows, grouped per (privacy_id,
        partition). Private partition selection uses the standard strategy
        over the compound accumulator's privacy-unit counts.
        """
        compound = combiners_lib.create_compound_combiner_with_custom_combiners(
            params, self._budget_accountant, params.custom_combiners)
        selection_spec = None
        if public_partitions is None:
            selection_spec = self._budget_accountant.request_budget(
                mechanism_type=MechanismType.GENERIC)

        # Host columns in float64: custom combiners receive the extracted
        # values exactly (the standard path's float32 encoding is for the
        # device kernels; user combiner logic must see what DPEngine sees).
        # Value-less pipelines (value_extractor=None / value column absent)
        # feed zeros, like DPEngine._extract_columns.
        if isinstance(col, encoding.EncodedColumns):
            # Pre-encoded dense-id columns: the float32 value column IS the
            # input format; promote it for the host combiner math.
            pid_e, pk_e, val_e, _, vocab_e = encoding.encode_rows(
                col, None if params.contribution_bounds_already_enforced
                else True, None, None, public_partitions=public_partitions)
            pid_col = (None if params.contribution_bounds_already_enforced
                       else pid_e)
            pk_col = pk_e
            value64 = np.asarray(val_e, dtype=np.float64)
            pre_encoded_vocab = vocab_e
        elif isinstance(col, encoding.ColumnarData):
            pre_encoded_vocab = None
            pid_col = (None if params.contribution_bounds_already_enforced
                       else np.asarray(col.pid))
            pk_col = np.asarray(col.pk)
            value64 = (np.zeros(len(pk_col))
                       if col.value is None else np.asarray(
                           col.value, dtype=np.float64))
        else:
            pre_encoded_vocab = None
            rows = list(col)
            pk_col = encoding._column_from_list(
                [data_extractors.partition_extractor(row) for row in rows])
            if params.contribution_bounds_already_enforced:
                pid_col = None
            else:
                pid_col = encoding._column_from_list(
                    [data_extractors.privacy_id_extractor(row)
                     for row in rows])
            if data_extractors.value_extractor is None:
                value64 = np.zeros(len(rows))
            else:
                value64 = np.asarray(
                    [data_extractors.value_extractor(row) for row in rows],
                    dtype=np.float64)
        if pre_encoded_vocab is not None:
            # encode_rows already applied the public filter and vocabulary.
            pk = pk_col
            pk_vocab = pre_encoded_vocab
        elif public_partitions is not None:
            pk_vocab = encoding.Vocabulary(list(public_partitions))
            pk = encoding._lookup_ids(pk_col, pk_vocab)
            in_public = pk >= 0
            pk = pk[in_public]
            value64 = value64[in_public]
            if pid_col is not None:
                pid_col = pid_col[in_public]
        else:
            pk, pk_uniques = encoding._factorize(pk_col)
            pk_vocab = encoding.Vocabulary.from_unique(pk_uniques)
        if pid_col is None:
            pid = np.arange(len(pk), dtype=np.int32)
        elif pre_encoded_vocab is not None:
            pid = np.asarray(pid_col, dtype=np.int32)
        else:
            pid, _ = encoding._factorize(pid_col)
        num_partitions = max(len(pk_vocab), 1)

        # Shared cap derivation with the standard path (the compound
        # gates Linf sampling; L1 mode samples per privacy unit;
        # perform_cross_partition_contribution_bounding=False disables L0
        # dropping while noise stays calibrated to the declared bound).
        linf_cap, l0_cap, l1_cap = derive_contribution_caps(
            params, compound, len(pid), num_partitions)
        if params.contribution_bounds_already_enforced:
            self._add_report_stage(
                "Contribution bounding: skipped (already enforced by the "
                "caller)")
        elif l1_cap is not None:
            self._add_report_stage(
                f"Total contribution bounding: for each privacy_id randomly "
                f"select max(actual_contributions, {l1_cap}) contributions "
                f"across all partitions")
        else:
            if compound.expects_per_partition_sampling():
                self._add_report_stage(
                    f"Per-partition contribution bounding: for each "
                    f"privacy_id and each partition, randomly select "
                    f"max(actual_contributions_per_partition, {linf_cap}) "
                    f"contributions.")
            if params.perform_cross_partition_contribution_bounding:
                self._add_report_stage(
                    f"Cross-partition contribution bounding: for each "
                    f"privacy_id randomly select max(actual_partition_"
                    f"contributed, {l0_cap}) partitions")
        if selection_spec is not None:
            self._add_report_stage(
                lambda: f"Private partition selection: using "
                        f"{params.partition_selection_strategy.value} "
                        f"method with (eps={selection_spec.eps}, "
                        f"delta={selection_spec.delta})")
        for stage in compound.explain_computation():
            self._add_report_stage(stage)
        key = self._next_key()
        key_counter = self._key_stream.counter
        engine = self

        def compute():
            engine._commit_release(key_counter)
            k_kernel, _ = jax.random.split(key)
            n_rows = len(pid)
            no_bounding = (params.contribution_bounds_already_enforced or
                           (linf_cap >= max(n_rows, 1) and
                            l0_cap >= num_partitions and l1_cap is None))
            if no_bounding or n_rows == 0:
                keep = np.ones(n_rows, dtype=bool)
            elif engine._mesh is not None:
                # Device bounding runs sharded over the mesh (pid-disjoint
                # shards, exact); the combiner loop below stays on host
                # with exact float64 values.
                from pipelinedp_tpu.parallel import sharded
                keep = sharded.host_row_mask(engine._mesh, k_kernel, pid,
                                             pk, linf_cap=linf_cap,
                                             l0_cap=l0_cap, l1_cap=l1_cap)
            else:
                keep = np.asarray(
                    columnar.bound_row_mask(k_kernel, jnp.asarray(pid),
                                            jnp.asarray(pk),
                                            jnp.ones(n_rows, dtype=bool),
                                            linf_cap, l0_cap,
                                            l1_cap=l1_cap))
            kpid, kpk, kval = pid[keep], pk[keep], value64[keep]
            # Host grouping: one lexsort, one accumulator per (pid, pk)
            # group, merged per partition (the reference's per-key
            # dataflow, collapsed).
            acc_by_pk = {}
            if len(kpid):
                order = np.lexsort((kpk, kpid))
                spid, spk, sval = kpid[order], kpk[order], kval[order]
                is_start = np.empty(len(spid), dtype=bool)
                is_start[0] = True
                np.not_equal(spid[1:], spid[:-1], out=is_start[1:])
                is_start[1:] |= spk[1:] != spk[:-1]
                starts = np.flatnonzero(is_start)
                ends = np.append(starts[1:], len(spid))
                for s, e in zip(starts, ends):
                    pk_id = int(spk[s])
                    acc = compound.create_accumulator(sval[s:e].tolist())
                    if pk_id in acc_by_pk:
                        acc = compound.merge_accumulators(
                            acc_by_pk[pk_id], acc)
                    acc_by_pk[pk_id] = acc
            if public_partitions is not None:
                # Empty public partitions release metrics too (parity:
                # DPEngine._add_empty_public_partitions).
                for pk_id in range(num_partitions):
                    if pk_id not in acc_by_pk:
                        acc_by_pk[pk_id] = compound.create_accumulator([])
                kept_ids = sorted(acc_by_pk)
            else:
                declared_l0 = (params.max_partitions_contributed
                               or params.max_contributions or 1)
                # With contribution_bounds_already_enforced each row is its
                # own encoded privacy unit: estimate true units by dividing
                # out the declared rows-per-unit bound (same adjustment as
                # the standard path / dp_engine.py).
                rows_per_unit = 1
                if params.contribution_bounds_already_enforced:
                    rows_per_unit = (params.max_contributions or
                                     params.max_contributions_per_partition)
                strategy = ps_lib.create_partition_selection_strategy(
                    params.partition_selection_strategy, selection_spec.eps,
                    selection_spec.delta, declared_l0,
                    params.pre_threshold)
                # Selection draws come from the secure sampler, not the
                # engine seed (same stance as the standard host path).
                kept_ids = sorted(
                    pk_id for pk_id, acc in acc_by_pk.items()
                    if strategy.should_keep(
                        int(np.ceil(acc[0] / rows_per_unit))))
            metrics = [
                compound.compute_metrics(acc_by_pk[pk_id])
                for pk_id in kept_ids
            ]
            return {
                "partition_id": np.asarray(kept_ids, dtype=np.int32),
                "keep_mask": np.ones(len(kept_ids), dtype=bool),
                "metrics": metrics,
            }

        return _LazyCustomResult(compute, pk_vocab)

    # -- execution (after budgets resolve) ----------------------------------

    def _execute(self, compound, params: AggregateParams, selection_spec,
                 key, pid, pk, value, num_partitions, linf_cap, l0_cap,
                 is_public: bool, is_vector: bool, l1_cap=None,
                 key_counter: int = -1, resident=None) -> dict:
        k_kernel, k_select, k_noise = jax.random.split(key, 3)
        n_rows = len(pid) if pid is not None else resident.n_rows
        has_quantile = any(
            isinstance(c, combiners_lib.QuantileCombiner)
            for c in compound.combiners)
        # Accumulators no combiner reads are never computed: each dropped
        # column saves two full-HBM segment passes in the kernel
        # (columnar.bound_and_aggregate need_* flags).
        need_flags = derive_need_flags(compound)
        # Group-level sum clipping exists only in the per-partition-bounds
        # mode; without it the kernel scatters rows straight to partitions.
        has_group_clip = bool(params.bounds_per_partition_are_set)
        row_lo, row_hi, glo, ghi, middle = derive_clip_bounds(params)

        vector_sums = None
        streamed_qhist = None
        norm_ord = {NormKind.Linf: 0, NormKind.L1: 1,
                    NormKind.L2: 2}[params.vector_norm_kind or NormKind.Linf]
        vec_sorted_kw = {}
        if is_vector:
            pid, pk, value, vec_sorted_kw = self._presort_vector_rows(
                pid, pk, value, n_rows, num_partitions, l1_cap)
        if resident is not None:
            # Resident-dataset replay: the session folds its retained
            # wire under this query's kernel key — no encode, no sort,
            # and (for device-resident handles / warm bound-cache hits)
            # no transfer or kernel either. Bit-identical to streaming
            # the source columns cold with the same key and chunk count.
            quantile_spec = None
            if has_quantile:
                if (self._mesh is not None
                        or not self._can_stream(True, num_partitions)):
                    raise NotImplementedError(
                        "PERCENTILE on a resident session needs the "
                        "streamed quantile path (single device, dense "
                        "[partitions, leaves] histogram within the "
                        "device budget)")
                quantile_spec = (
                    quantile_tree_lib.DEFAULT_BRANCHING_FACTOR
                    ** quantile_tree_lib.DEFAULT_TREE_HEIGHT,
                    params.min_value, params.max_value)
            accs = resident._accumulate(
                k_kernel,
                mesh=self._mesh,
                linf_cap=linf_cap,
                l0_cap=l0_cap,
                row_clip_lo=row_lo,
                row_clip_hi=row_hi,
                middle=middle,
                group_clip_lo=glo,
                group_clip_hi=ghi,
                l1_cap=l1_cap,
                need_flags=need_flags,
                has_group_clip=has_group_clip,
                quantile_spec=quantile_spec,
                segment_sort=self._segment_sort,
                compact_merge=self._compact_merge,
                resilience=self._stream_resilience(key_counter))
            if quantile_spec is not None:
                accs, streamed_qhist = accs
        elif self._mesh is not None:
            from pipelinedp_tpu.parallel import sharded
            if (not is_vector and not has_quantile and
                    self._stream_chunks != 1 and
                    self._transfer_encoding != "bytes" and
                    (self._stream_chunks is not None or
                     n_rows >= streaming.MIN_STREAM_ROWS)):
                # Large mesh input: chunked wire-codec ingest — each
                # chunk's sharded device_put overlaps the previous chunk's
                # kernels (parallel/sharded.stream_bound_and_aggregate).
                accs = sharded.stream_bound_and_aggregate(
                    self._mesh, k_kernel, pid, pk, value,
                    num_partitions=num_partitions,
                    linf_cap=linf_cap,
                    l0_cap=l0_cap,
                    row_clip_lo=row_lo,
                    row_clip_hi=row_hi,
                    middle=middle,
                    group_clip_lo=glo,
                    group_clip_hi=ghi,
                    l1_cap=l1_cap,
                    n_chunks=self._stream_chunks,
                    value_transfer_dtype=self._value_transfer_dtype,
                    need_flags=need_flags,
                    has_group_clip=has_group_clip,
                    resilience=self._stream_resilience(key_counter),
                    compact_merge=self._compact_merge,
                    segment_sort=self._segment_sort)
            else:
                # Stage (hash-shard + device_put) once; both the aggregate
                # and the quantile-histogram kernels reuse the staged
                # arrays.
                valid_rows = np.ones(n_rows, dtype=bool)
                pid, pk, value, valid_rows = sharded.stage_rows(
                    self._mesh, pid, pk, value, valid_rows)
                if is_vector:
                    vector_sums, accs = sharded.bound_and_aggregate_vector(
                        self._mesh, k_kernel, pid, pk, value, valid_rows,
                        num_partitions=num_partitions,
                        linf_cap=linf_cap,
                        l0_cap=l0_cap,
                        max_norm=params.vector_max_norm,
                        norm_ord=norm_ord,
                        l1_cap=l1_cap,
                        **vec_sorted_kw)
                else:
                    accs = sharded.bound_and_aggregate(
                        self._mesh, k_kernel, pid, pk, value, valid_rows,
                        num_partitions=num_partitions,
                        linf_cap=linf_cap,
                        l0_cap=l0_cap,
                        row_clip_lo=row_lo,
                        row_clip_hi=row_hi,
                        middle=middle,
                        group_clip_lo=glo,
                        group_clip_hi=ghi,
                        l1_cap=l1_cap,
                        need_flags=need_flags,
                        has_group_clip=has_group_clip)
        elif is_vector:
            vector_sums, accs = columnar.bound_and_aggregate_vector(
                k_kernel, jnp.asarray(pid), jnp.asarray(pk),
                jnp.asarray(value), jnp.ones(n_rows, dtype=bool),
                num_partitions=num_partitions,
                linf_cap=linf_cap,
                l0_cap=l0_cap,
                max_norm=params.vector_max_norm,
                norm_ord=norm_ord,
                l1_cap=l1_cap,
                **vec_sorted_kw)
        elif (self._can_stream(has_quantile, num_partitions) and
              self._stream_chunks != 1 and
              (self._stream_chunks is not None or
               n_rows >= streaming.MIN_STREAM_ROWS)):
            # Large single-device input: pid-disjoint chunked pipeline so
            # the host->device transfer overlaps the kernel and ships
            # wire-codec-compressed columns (ops/streaming.py; exact, see
            # module doc). PERCENTILE rides the same stream: quantile-tree
            # leaf counts are additive across the pid-disjoint chunks.
            quantile_spec = None
            if has_quantile:
                quantile_spec = (
                    quantile_tree_lib.DEFAULT_BRANCHING_FACTOR
                    ** quantile_tree_lib.DEFAULT_TREE_HEIGHT,
                    params.min_value, params.max_value)
            accs = streaming.stream_bound_and_aggregate(
                k_kernel, pid, pk, value,
                num_partitions=num_partitions,
                linf_cap=linf_cap,
                l0_cap=l0_cap,
                row_clip_lo=row_lo,
                row_clip_hi=row_hi,
                middle=middle,
                group_clip_lo=glo,
                group_clip_hi=ghi,
                l1_cap=l1_cap,
                n_chunks=self._stream_chunks,
                value_transfer_dtype=self._value_transfer_dtype,
                need_flags=need_flags,
                has_group_clip=has_group_clip,
                transfer_encoding=self._transfer_encoding,
                quantile_spec=quantile_spec,
                resilience=self._stream_resilience(key_counter),
                compact_merge=self._compact_merge,
                segment_sort=self._segment_sort)
            if has_quantile:
                accs, streamed_qhist = accs
        else:
            accs = columnar.bound_and_aggregate(
                k_kernel, jnp.asarray(pid), jnp.asarray(pk),
                jnp.asarray(value), jnp.ones(n_rows, dtype=bool),
                num_partitions=num_partitions,
                linf_cap=linf_cap,
                l0_cap=l0_cap,
                row_clip_lo=row_lo,
                row_clip_hi=row_hi,
                middle=middle,
                group_clip_lo=glo,
                group_clip_hi=ghi,
                l1_cap=l1_cap,
                need_count=need_flags[0],
                need_sum=need_flags[1],
                need_norm=need_flags[2],
                need_norm_sq=need_flags[3],
                has_group_clip=has_group_clip)

        # At-most-once release: the token commits BEFORE any noise is
        # drawn (the quantile noise below and the finalize epilogue), so
        # a resumed or retried run that already released under this
        # KeyStream state refuses instead of re-drawing — and a crash
        # between commit and publication errs on the side of zero
        # releases, never two (RESILIENCE.md).
        self._commit_release(key_counter)

        # On a mesh the accumulators are padded so the partition dimension
        # shards evenly; all downstream math runs on the padded arrays and
        # the final columns are trimmed back to num_partitions.
        num_out = int(accs.pid_count.shape[0])
        partition_exists = accs.pid_count > 0

        # PERCENTILE: dense [num_partitions, leaves] histograms feed every
        # partition's quantile tree at once; partition counts beyond the
        # device budget process in partition blocks over pk-sorted rows
        # (ops/quantiles.py). Computed up front so the combiner loop only
        # reads finished columns.
        quantile_cols = None
        if has_quantile:
            qcombiner = next(
                c for c in compound.combiners
                if isinstance(c, combiners_lib.QuantileCombiner))
            # k_kernel is handed out a second time on purpose: the
            # quantile path must *replay* the fused kernel's sampling
            # decisions (identical keep mask, see _quantile_columns
            # docstring), not draw an independent sample.
            # dplint: disable=DPL001 — deliberate replay of the bounding mask
            quantile_cols = self._quantile_columns(
                qcombiner, pid, pk, value, n_rows, num_out,
                num_partitions, linf_cap, l0_cap, l1_cap, k_kernel,
                KeyStream.derive(k_noise, KeyTag.QUANTILE_NOISE),
                valid_rows if self._mesh is not None else None,
                precomputed_hist=streamed_qhist)

        if self._fused_epilogue:
            return self._fused_finalize(compound, params, selection_spec,
                                        k_select, k_noise, accs, vector_sums,
                                        quantile_cols, num_partitions,
                                        is_public)
        return self._legacy_finalize(compound, params, selection_spec,
                                     k_select, k_noise, accs, vector_sums,
                                     quantile_cols, num_partitions, num_out,
                                     partition_exists, is_public)

    def _fused_finalize(self, compound, params, selection_spec, k_select,
                        k_noise, accs, vector_sums, quantile_cols,
                        num_partitions, is_public) -> dict:
        """The fused epilogue: plan construction + one dispatch + one
        batched device→host transfer (ops/finalize.py)."""
        max_rows_per_pid = 1
        if (selection_spec is not None
                and params.contribution_bounds_already_enforced):
            max_rows_per_pid = (params.max_contributions
                                or params.max_contributions_per_partition)
        plan, scalars = finalize_ops.build_plan(
            compound.combiners,
            params,
            selection_spec,
            is_public=is_public,
            num_partitions=num_partitions,
            max_rows_per_pid=max_rows_per_pid)
        t_fin0 = time.perf_counter()
        with profiler.stage("dp/finalize"), \
                obs_trace.span("engine/finalize",
                               secure_host_noise=self._secure_host_noise,
                               n_metrics=len(compound.combiners)):
            if self._secure_host_noise:
                # One batched device→host transfer of every device-resident
                # input; selection, noise and metric math then run in
                # float64 numpy with noise_core's full granularity
                # snapping.
                with profiler.stage("dp/finalize_transfer"):
                    # dplint: disable=DPL007 — secure-host-noise path: this transfer IS the mechanism boundary; host_epilogue adds float64 noise_core noise before anything is released
                    host_accs, host_vec = jax.device_get(
                        (accs, vector_sums))
                metric_cols, keep = finalize_ops.host_epilogue(
                    plan, scalars, host_accs, host_vec)
            else:
                operands = finalize_ops.device_operands(
                    plan, scalars, accs, vector_sums, k_select, k_noise)
                if self._mesh is not None:
                    from pipelinedp_tpu.parallel import sharded
                    builder = functools.partial(
                        sharded.build_finalize_epilogue, self._mesh)
                else:
                    builder = None
                epilogue = self._epilogue_cache.get(plan,
                                                    self._mesh,
                                                    operands,
                                                    builder=builder)
                device_cols, device_keep = epilogue(operands)
                with profiler.stage("dp/finalize_transfer"):
                    metric_cols, keep = jax.device_get(
                        (device_cols, device_keep))
        obs_metrics.finalize_seconds().observe(
            time.perf_counter() - t_fin0)
        return finalize_ops.materialize(plan, scalars, metric_cols, keep,
                                        quantile_cols=quantile_cols)

    def _legacy_finalize(self, compound, params, selection_spec, k_select,
                         k_noise, accs, vector_sums, quantile_cols,
                         num_partitions, num_out, partition_exists,
                         is_public) -> dict:
        """The unfused per-combiner epilogue loop (fused_epilogue=False):
        one device op + blocking sync per metric. Kept as the parity
        oracle — tests/finalize_test.py pins the fused epilogue
        bit-identical to this path for seeded device-noise runs."""
        # Partition selection. The selection strategy's L0 sensitivity is
        # the *declared* cross-partition bound: max_partitions_contributed,
        # or max_contributions in L1 mode (the per-privacy-id total sample
        # of at most k rows reaches at most k partitions).
        if is_public:
            keep_mask = jnp.arange(num_out) < num_partitions
        elif selection_spec is not None:
            declared_l0 = (params.max_partitions_contributed
                           or params.max_contributions or 1)
            max_rows_per_pid = 1
            if params.contribution_bounds_already_enforced:
                max_rows_per_pid = (params.max_contributions or
                                    params.max_contributions_per_partition)
            pid_counts_est = jnp.ceil(accs.pid_count / max_rows_per_pid)
            strategy = ps_lib.create_partition_selection_strategy(
                params.partition_selection_strategy, selection_spec.eps,
                selection_spec.delta, declared_l0, params.pre_threshold)
            keep_mask, _ = self._apply_selection(k_select, pid_counts_est,
                                                 partition_exists, strategy)
        else:
            keep_mask = partition_exists  # post-agg thresholding prunes below

        # DP metrics per combiner, batched noise.
        columns = {}
        for i, combiner in enumerate(compound.combiners):
            sub_key = KeyStream.derive(k_noise, i)
            self._compute_combiner_metrics(combiner, params, accs,
                                           vector_sums, sub_key, columns,
                                           quantile_cols=quantile_cols)
            if isinstance(combiner,
                          combiners_lib.PostAggregationThresholdingCombiner):
                thresh = dp_computations.create_thresholding_mechanism(
                    combiner.mechanism_spec(), combiner.sensitivities(),
                    params.pre_threshold)
                # _compute_combiner_metrics is a no-op for the thresholding
                # combiner (handled right here), so sub_key has exactly one
                # runtime consumer on this branch.
                # dplint: disable=DPL001 — single runtime consumer per branch
                thresh_keep, noised = self._apply_selection(
                    sub_key, accs.pid_count, partition_exists,
                    thresh.strategy)
                keep_mask = keep_mask & thresh_keep
                columns["privacy_id_count"] = noised
                if params.output_noise_stddev:
                    columns["privacy_id_count_noise_stddev"] = np.full(
                        num_out, float(thresh.strategy.noise_stddev),
                        dtype=np.float64)

        # Mask metrics of non-kept partitions: direct consumers of the
        # columns must not see values partition selection dropped. Mesh
        # padding partitions are trimmed here.
        keep_np = np.asarray(keep_mask)[:num_partitions]
        for name, col in columns.items():
            arr = np.asarray(col)[:num_partitions]
            mask = keep_np if arr.ndim == 1 else keep_np[:, None]
            columns[name] = np.where(mask, arr, np.nan)
        columns["partition_id"] = np.arange(num_partitions, dtype=np.int32)
        columns["keep_mask"] = keep_np
        return columns

    def _commit_release(self, key_counter: int,
                        kind: str = "noise_release") -> None:
        """At-most-once gate for every release-producing entry point:
        commits (root fingerprint, KeyStream counter) to the engine's
        ReleaseJournal before any randomness is drawn; no-op without a
        journal (the reference's semantics — re-release is the caller's
        accounting decision)."""
        if self._release_journal is not None:
            self._release_journal.commit(
                finalize_ops.release_token(self._key_stream.fingerprint(),
                                           key_counter), kind=kind)

    def _stream_resilience(self, key_counter: int):
        """The runtime.StreamResilience bundle for a streamed execution,
        or None when no resilience knob is set (fail-fast, zero
        overhead — the historical behavior)."""
        if (self._checkpoint_policy is None and self._retry_policy is None
                and self._fault_injector is None
                and self._watchdog_timeout_s is None):
            return None
        from pipelinedp_tpu import runtime as runtime_lib
        return runtime_lib.StreamResilience(
            retry_policy=(self._retry_policy if self._retry_policy is not None
                          else runtime_lib.RetryPolicy()),
            fault_injector=self._fault_injector,
            checkpoint_policy=self._checkpoint_policy,
            key_counter=key_counter,
            watchdog_timeout_s=self._watchdog_timeout_s)

    def _presort_vector_rows(self, pid, pk, value, n_rows: int,
                             num_partitions: int, l1_cap):
        """Host presort enabling the packed 3-key bounding sort on the
        VECTOR_SUM path -> (pid, pk, value, kernel kwargs).

        The vector path has no wire codec delivering pid-sorted rows for
        free, so a stable host argsort buys the packed 4-operand sampler
        sort (columnar.bound_and_aggregate_vector pid_sorted — vs the
        general path's 7 operands). Same sampling distribution, different
        draws than the unsorted kernel, so segment_sort=False restores
        the legacy draw-for-draw behavior. On a mesh the stable shard
        partition (shard_rows_by_pid) preserves in-shard order, so every
        device's block stays pid-sorted; the global distinct-pid count
        bounds each shard's segments. L1 mode keeps the general sampler
        (the packed layout has no L1 pre-sample), as does a packed
        layout that does not fit this shape (presorted_fits).
        """
        no_sort_kw: dict = {}
        if (self._segment_sort is False or l1_cap is not None
                or n_rows == 0 or isinstance(pid, jax.Array)):
            return pid, pk, value, no_sort_kw
        p_fit = num_partitions
        if self._mesh is not None:
            from pipelinedp_tpu.parallel import sharded
            p_fit = sharded.padded_num_partitions(self._mesh,
                                                  num_partitions)
        pid = np.asarray(pid)
        order = np.argsort(pid, kind="stable")
        spid = pid[order]
        distinct = 1 + int(np.count_nonzero(np.diff(spid)))
        max_segments = wirecodec.round_ucap(distinct)
        if not columnar.presorted_fits(n_rows, p_fit, max_segments):
            return pid, pk, value, no_sort_kw
        return (spid, np.asarray(pk)[order], np.asarray(value)[order],
                dict(pid_sorted=True, max_segments=max_segments))

    def _can_stream(self, has_quantile: bool, num_partitions: int) -> bool:
        """PERCENTILE can ride the stream when the dense [partitions,
        leaves] histogram fits the device budget (the partition-blocked
        quantile path needs pk-sorted residency, which is incompatible
        with pid-chunking) and the wire codec is in use."""
        if not has_quantile:
            return True
        if self._transfer_encoding == "bytes":
            return False
        num_leaves = (quantile_tree_lib.DEFAULT_BRANCHING_FACTOR
                      ** quantile_tree_lib.DEFAULT_TREE_HEIGHT)
        return (num_partitions * num_leaves
                <= quantile_ops.MAX_HISTOGRAM_ELEMENTS)

    # -- selection dispatch: secure host path or device kernel --------------

    def _apply_selection(self, key, counts, exists, strategy):
        """(keep_mask, noised_counts) from a host strategy object.

        The single dispatch point between the float64 secure host path
        (strategy.select_vec) and the device kernel
        (ops/selection.select_partitions) — every selection decision
        (private partition selection, post-aggregation thresholding,
        select_partitions) routes through here.
        """
        with profiler.stage("dp/partition_selection"):
            if self._secure_host_noise:
                keep, noised = strategy.select_vec(np.asarray(counts))
                return keep & np.asarray(exists), noised
            sel_params = selection_ops.selection_params_from_strategy(
                strategy)
            # Compiled entry: selection bits must not depend on whether the
            # kernel runs standalone or inlined in the fused epilogue.
            return selection_ops.select_partitions_jit(key, counts,
                                                       sel_params, exists)

    # -- noise dispatch: device kernels or float64 host finalization --------

    def _add_noise(self, key, values, is_gaussian, scale_or_std, granularity):
        with profiler.stage("dp/noise"):
            if self._secure_host_noise:
                return noise_core.add_noise_array(np.asarray(values),
                                                  bool(is_gaussian),
                                                  float(scale_or_std))
            return noise_ops.add_noise_compiled(key, jnp.asarray(values),
                                                is_gaussian, scale_or_std,
                                                granularity)

    def _add_laplace(self, key, values, scale, granularity):
        if self._secure_host_noise:
            return noise_core.add_laplace_noise_array(np.asarray(values),
                                                      float(scale))
        return noise_ops.add_laplace_noise_compiled(key, jnp.asarray(values),
                                                    scale, granularity)

    def _add_gaussian(self, key, values, stddev, granularity):
        if self._secure_host_noise:
            return noise_core.add_gaussian_noise_array(np.asarray(values),
                                                       float(stddev))
        return noise_ops.add_gaussian_noise_compiled(key,
                                                     jnp.asarray(values),
                                                     stddev, granularity)

    @staticmethod
    def _noise_stddev_column(columns: dict, name: str, is_gaussian,
                             scale_or_std, n: int) -> None:
        """[n] constant column stating the added noise's stddev (wired when
        params.output_noise_stddev — see aggregate_params.py)."""
        std = (float(scale_or_std)
               if is_gaussian else float(scale_or_std) * math.sqrt(2.0))
        columns[f"{name}_noise_stddev"] = np.full(n, std, dtype=np.float64)

    def _compute_combiner_metrics(self, combiner, params, accs, vector_sums,
                                  key, columns: dict,
                                  quantile_cols=None) -> None:
        k1, k2, k3 = jax.random.split(key, 3)
        n_out = int(np.asarray(accs.pid_count).shape[0])
        if isinstance(combiner, combiners_lib.CountCombiner):
            is_g, scale, gran = _mechanism_noise_params(
                combiner.mechanism_spec(), combiner.sensitivities())
            columns["count"] = self._add_noise(k1, accs.count, is_g, scale,
                                               gran)
            if params.output_noise_stddev:
                self._noise_stddev_column(columns, "count", is_g, scale,
                                          n_out)
        elif isinstance(combiner, combiners_lib.SumCombiner):
            is_g, scale, gran = _mechanism_noise_params(
                combiner.mechanism_spec(), combiner.sensitivities())
            columns["sum"] = self._add_noise(k1, accs.sum, is_g, scale, gran)
            if params.output_noise_stddev:
                self._noise_stddev_column(columns, "sum", is_g, scale, n_out)
        elif isinstance(combiner, combiners_lib.PrivacyIdCountCombiner):
            is_g, scale, gran = _mechanism_noise_params(
                combiner.mechanism_spec(), combiner.sensitivities())
            columns["privacy_id_count"] = self._add_noise(
                k1, accs.pid_count, is_g, scale, gran)
            if params.output_noise_stddev:
                self._noise_stddev_column(columns, "privacy_id_count", is_g,
                                          scale, n_out)
        elif isinstance(combiner,
                        combiners_lib.PostAggregationThresholdingCombiner):
            pass  # handled by the caller (needs the keep mask)
        elif isinstance(combiner, combiners_lib.MeanCombiner):
            count_spec, sum_spec = combiner.mechanism_spec()
            cg, cs, cgr = _mechanism_noise_params(
                count_spec, combiner._count_sensitivities)
            sg, ss, sgr = _mechanism_noise_params(
                sum_spec, combiner._sum_sensitivities)
            dp_count = self._add_noise(k1, accs.count, cg, cs, cgr)
            dp_norm_sum = self._add_noise(k2, accs.norm_sum, sg, ss, sgr)
            middle = dp_computations.compute_middle(params.min_value,
                                                    params.max_value)
            # np on the host path keeps the float64 width of the secure
            # noise; jnp would silently downcast to float32.
            xp = np if self._secure_host_noise else jnp
            dp_mean = middle + dp_norm_sum / xp.maximum(1.0, dp_count)
            columns["mean"] = dp_mean
            if "count" in combiner.metrics_names():
                columns["count"] = dp_count
            if "sum" in combiner.metrics_names():
                columns["sum"] = dp_mean * dp_count
        elif isinstance(combiner, combiners_lib.VarianceCombiner):
            self._variance_metrics(combiner, params, accs, (k1, k2, k3),
                                   columns)
        elif isinstance(combiner, combiners_lib.QuantileCombiner):
            # Columns precomputed by _quantile_columns (dense or blocked).
            for i, name in enumerate(combiner.metrics_names()):
                columns[name] = quantile_cols[:, i]
        elif isinstance(combiner, combiners_lib.VectorSumCombiner):
            p = combiner._params
            noise_params = p.additive_vector_noise_params
            if noise_params.noise_kind == NoiseKind.LAPLACE:
                l1 = (noise_params.l0_sensitivity *
                      noise_params.linf_sensitivity)
                scale = l1 / noise_params.eps_per_coordinate
                gran = noise_core.laplace_granularity(scale)
                columns["vector_sum"] = self._add_laplace(
                    k1, vector_sums, scale, gran)
                if params.output_noise_stddev:
                    self._noise_stddev_column(columns, "vector_sum", False,
                                              scale, n_out)
            else:
                l2 = (math.sqrt(noise_params.l0_sensitivity) *
                      noise_params.linf_sensitivity)
                sigma = noise_core.analytic_gaussian_sigma(
                    noise_params.eps_per_coordinate,
                    noise_params.delta_per_coordinate, l2)
                gran = noise_core.gaussian_granularity(sigma)
                columns["vector_sum"] = self._add_gaussian(
                    k1, vector_sums, sigma, gran)
                if params.output_noise_stddev:
                    self._noise_stddev_column(columns, "vector_sum", True,
                                              sigma, n_out)
        else:
            raise NotImplementedError(
                f"Combiner {type(combiner).__name__} is not supported on the "
                f"columnar engine.")

    def _quantile_columns(self, combiner, pid, pk, value, n_rows,
                          num_out, num_partitions, linf_cap, l0_cap, l1_cap,
                          k_kernel, k_noise, mesh_valid_rows,
                          precomputed_hist=None):
        """[num_out, n_quantiles] DP quantile estimates for every
        partition. Dense single-histogram path when the [partitions,
        leaves] layout fits the device budget; otherwise partition-blocked
        over pk-sorted rows (ops/quantiles.blocked_quantile_columns). The
        row keep mask replays the fused kernel's sampling decisions (same
        PRNG key). precomputed_hist: the [num_out, leaves] leaf histogram
        already accumulated by the streamed path (chunk-additive)."""
        p = combiner._params.aggregate_params
        eps, delta = combiner._params.eps, combiner._params.delta
        is_gaussian = p.noise_kind == NoiseKind.GAUSSIAN
        branching = quantile_tree_lib.DEFAULT_BRANCHING_FACTOR
        height = quantile_tree_lib.DEFAULT_TREE_HEIGHT
        num_leaves = branching**height
        quantiles = combiner._quantiles_to_compute
        noise_counter = [0]

        def noise_fn(levels):
            if self._secure_host_noise:
                return quantile_ops.noised_levels_host(
                    [np.asarray(lvl) for lvl in levels], eps, delta,
                    p.max_partitions_contributed,
                    p.max_contributions_per_partition, is_gaussian)
            noise_counter[0] += 1
            return quantile_ops.noised_levels_device(
                KeyStream.derive(k_noise, noise_counter[0]), levels, eps,
                delta, p.max_partitions_contributed,
                p.max_contributions_per_partition, is_gaussian)

        def finish(hist):
            # Device-noise mode keeps hist -> levels -> noise -> walk all
            # on device ([partitions, quantiles] is the only download);
            # the secure host path pulls the levels once and finishes in
            # float64 numpy. Used for the dense histogram and per block.
            levels = quantile_ops.level_counts(hist, branching, height)
            noised = noise_fn(levels)
            if self._secure_host_noise:
                return quantile_ops.walk_quantiles(noised, quantiles,
                                                   p.min_value, p.max_value,
                                                   branching)
            return np.asarray(
                quantile_ops.walk_quantiles_device(
                    noised, jnp.asarray(quantiles, dtype=jnp.float32),
                    p.min_value, p.max_value, branching=branching))

        if precomputed_hist is not None:
            return finish(precomputed_hist)
        dense_fits = num_out * num_leaves <= quantile_ops.MAX_HISTOGRAM_ELEMENTS
        if self._mesh is not None:
            from pipelinedp_tpu.parallel import sharded
            if not dense_fits:
                # Partition-blocked under the mesh: one sharded bounding
                # mask, then a sharded histogram + reduce-scatter per
                # block (sharded.blocked_quantile_columns).
                return sharded.blocked_quantile_columns(
                    self._mesh, k_kernel, pid, pk, value, mesh_valid_rows,
                    num_partitions=num_out,
                    num_leaves=num_leaves,
                    lower=p.min_value,
                    upper=p.max_value,
                    linf_cap=linf_cap,
                    l0_cap=l0_cap,
                    num_quantiles=len(quantiles),
                    finish_fn=finish,
                    l1_cap=l1_cap)
            hist = sharded.quantile_leaf_histograms(
                self._mesh, k_kernel, pid, pk, value, mesh_valid_rows,
                num_partitions=num_partitions,
                num_leaves=num_leaves,
                lower=p.min_value,
                upper=p.max_value,
                linf_cap=linf_cap,
                l0_cap=l0_cap,
                l1_cap=l1_cap)
            return finish(hist)
        row_keep = columnar.bound_row_mask(k_kernel, jnp.asarray(pid),
                                           jnp.asarray(pk),
                                           jnp.ones(n_rows, dtype=bool),
                                           linf_cap, l0_cap, l1_cap=l1_cap)
        if dense_fits:
            hist = quantile_ops.leaf_histograms(jnp.asarray(pk),
                                                jnp.asarray(value),
                                                row_keep,
                                                num_partitions=num_out,
                                                num_leaves=num_leaves,
                                                lower=p.min_value,
                                                upper=p.max_value)
            return finish(hist)
        # Blocked path: sort rows by partition on device once; each block
        # histograms a contiguous row range.
        dpk = jnp.asarray(pk)
        order = jnp.argsort(dpk)
        spk = dpk[order]
        sval = jnp.asarray(value)[order]
        skeep = row_keep[order]
        row_bounds = np.concatenate(
            [[0], np.cumsum(np.bincount(pk, minlength=num_out))])
        return quantile_ops.blocked_quantile_columns(
            spk, sval, skeep, row_bounds,
            num_partitions=num_out,
            num_leaves=num_leaves,
            lower=p.min_value,
            upper=p.max_value,
            num_quantiles=len(quantiles),
            finish_fn=finish)

    def _variance_metrics(self, combiner, params, accs, keys, columns):
        """Vectorized twin of dp_computations.compute_dp_var."""
        k1, k2, k3 = keys
        p = combiner._params
        eps, delta = p.eps, p.delta
        (b_count, b_sum, b_sq) = dp_computations.equally_split_budget(
            eps, delta, 3)
        l0 = params.max_partitions_contributed
        linf = params.max_contributions_per_partition
        noise_kind = params.noise_kind
        middle = dp_computations.compute_middle(params.min_value,
                                                params.max_value)

        def noise_arr(k, arr, eps_delta, linf_sens):
            if linf_sens == 0:
                return arr
            if noise_kind == NoiseKind.GAUSSIAN:
                sigma = noise_core.analytic_gaussian_sigma(
                    eps_delta[0], eps_delta[1],
                    dp_computations.compute_l2_sensitivity(l0, linf_sens))
                return self._add_gaussian(
                    k, arr, sigma, noise_core.gaussian_granularity(sigma))
            scale = noise_core.laplace_diversity(
                eps_delta[0],
                dp_computations.compute_l1_sensitivity(l0, linf_sens))
            return self._add_laplace(
                k, arr, scale, noise_core.laplace_granularity(scale))

        xp = np if self._secure_host_noise else jnp
        dp_count = noise_arr(k1, accs.count, b_count, linf)
        count_clamped = xp.maximum(1.0, dp_count)
        sum_linf = linf * abs(middle - params.min_value)
        dp_mean_normalized = noise_arr(k2, accs.norm_sum, b_sum,
                                       sum_linf) / count_clamped
        # Noise calibration for the sum of squares uses the squares interval
        # of the raw values (scalar twin: compute_dp_var,
        # dp_computations.py:306-365 — interval feeds sensitivity only, the
        # accumulated normalized sum of squares itself is noised as-is).
        sq_lo, sq_hi = dp_computations.compute_squares_interval(
            params.min_value, params.max_value)
        sq_middle = dp_computations.compute_middle(sq_lo, sq_hi)
        sq_linf = linf * abs(sq_middle - sq_lo)
        dp_mean_sq = noise_arr(k3, accs.norm_sq_sum, b_sq,
                               sq_linf) / count_clamped
        if self._secure_host_noise:
            dp_var = dp_mean_sq - dp_mean_normalized**2
        else:
            # Compiled: identical FMA contraction to the fused epilogue.
            dp_var = finalize_ops.variance_from_moments(dp_mean_sq,
                                                        dp_mean_normalized)
        # Parity with compute_dp_var: the middle is added only for a proper
        # range (when min == max the normalized mean is reported as-is).
        dp_mean = dp_mean_normalized + (
            middle if params.min_value != params.max_value else 0.0)
        columns["variance"] = dp_var
        if "mean" in combiner.metrics_names():
            columns["mean"] = dp_mean
        if "count" in combiner.metrics_names():
            columns["count"] = dp_count
        if "sum" in combiner.metrics_names():
            columns["sum"] = dp_mean * dp_count
