"""Mergeable DP quantile trees (native equivalent of PyDP's quantile_tree).

The reference computes DP percentiles with Google's C++ QuantileTree through
PyDP (combiners.py:26, 590-669; defaults height=4, branching=16 at
combiners.py:653-654). This is a from-scratch implementation of the same
algorithm with a TPU-friendly dense layout: the tree state is a single
int64 leaf-count array of size branching**height; internal levels are
derived by reshape-sums. That makes accumulators fixed-shape arrays — they
merge by addition (a segment-reduce on device), and serialize to raw bytes.

Quantile estimation walks the tree from the root: each level is an
independent histogram query that gets 1/height of the budget; per-node noise
uses sensitivity l0 * linf per level (each entry increments exactly one node
per level). Noised child counts are clamped to >= 0 and the walk descends
into the child where the target rank falls, finishing with linear
interpolation inside the leaf interval.
"""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from pipelinedp_tpu import noise_core

DEFAULT_TREE_HEIGHT = 4
DEFAULT_BRANCHING_FACTOR = 16

_MAGIC = b"QTR1"


class QuantileTreeSummary:
    """Serialized, mergeable tree state."""

    def __init__(self, data: bytes):
        self._data = data

    def to_bytes(self) -> bytes:
        return self._data


def bytes_to_summary(data: bytes) -> QuantileTreeSummary:
    return QuantileTreeSummary(data)


class QuantileTree:
    """DP quantile sketch over [lower, upper].

    API parity with pydp.algorithms.quantile_tree.QuantileTree:
    ``add_entry``, ``merge``, ``serialize``, ``compute_quantiles``.
    """

    def __init__(self,
                 lower: float,
                 upper: float,
                 tree_height: int = DEFAULT_TREE_HEIGHT,
                 branching_factor: int = DEFAULT_BRANCHING_FACTOR):
        if not lower < upper:
            raise ValueError(f"lower must be < upper: {lower} >= {upper}")
        if tree_height < 1:
            raise ValueError(f"tree_height must be >= 1: {tree_height}")
        if branching_factor < 2:
            raise ValueError(
                f"branching_factor must be >= 2: {branching_factor}")
        self._lower = float(lower)
        self._upper = float(upper)
        self._height = int(tree_height)
        self._branching = int(branching_factor)
        self._num_leaves = self._branching**self._height
        self._leaf_counts = np.zeros(self._num_leaves, dtype=np.int64)

    @property
    def leaf_counts(self) -> np.ndarray:
        return self._leaf_counts

    @property
    def height(self) -> int:
        return self._height

    @property
    def branching_factor(self) -> int:
        return self._branching

    def _leaf_index(self, value: float) -> int:
        clamped = min(max(value, self._lower), self._upper)
        frac = (clamped - self._lower) / (self._upper - self._lower)
        return min(int(frac * self._num_leaves), self._num_leaves - 1)

    def add_entry(self, value: float) -> None:
        self._leaf_counts[self._leaf_index(value)] += 1

    def add_entries(self, values: Sequence[float]) -> None:
        """Batched add (vectorized; not in the PyDP API but same semantics)."""
        values = np.asarray(values, dtype=np.float64)
        clamped = np.clip(values, self._lower, self._upper)
        frac = (clamped - self._lower) / (self._upper - self._lower)
        idx = np.minimum((frac * self._num_leaves).astype(np.int64),
                         self._num_leaves - 1)
        np.add.at(self._leaf_counts, idx, 1)

    # -- serialization ------------------------------------------------------

    def serialize(self) -> QuantileTreeSummary:
        header = _MAGIC + struct.pack("<ddii", self._lower, self._upper,
                                      self._height, self._branching)
        return QuantileTreeSummary(header + self._leaf_counts.tobytes())

    def merge(self, summary: QuantileTreeSummary) -> None:
        data = summary.to_bytes()
        if data[:4] != _MAGIC:
            raise ValueError("Invalid quantile tree summary.")
        lower, upper, height, branching = struct.unpack("<ddii", data[4:28])
        if (lower, upper, height, branching) != (self._lower, self._upper,
                                                 self._height,
                                                 self._branching):
            raise ValueError(
                "Cannot merge quantile trees with different parameters: "
                f"{(lower, upper, height, branching)} != "
                f"{(self._lower, self._upper, self._height, self._branching)}")
        counts = np.frombuffer(data[28:], dtype=np.int64)
        if len(counts) != self._num_leaves:
            raise ValueError("Corrupt quantile tree summary.")
        self._leaf_counts = self._leaf_counts + counts

    # -- quantile computation ----------------------------------------------

    def _level_counts(self, level: int) -> np.ndarray:
        """Counts at a level (0 = children of root, height-1 = leaves)."""
        nodes = self._branching**(level + 1)
        return self._leaf_counts.reshape(nodes, -1).sum(axis=1)

    def compute_quantiles(self, eps: float, delta: float, l0_sensitivity: int,
                          linf_sensitivity: float, quantiles: Sequence[float],
                          noise_type: str) -> List[float]:
        """DP estimates of the given quantiles (each in [0, 1]).

        Budget is split evenly across tree levels; each level is one
        histogram query with per-entry sensitivity l0 * linf.
        """
        if any(not 0 <= q <= 1 for q in quantiles):
            raise ValueError(f"quantiles must be in [0, 1]: {quantiles}")
        eps_per_level = eps / self._height
        delta_per_level = delta / self._height
        noised_levels = []
        for level in range(self._height):
            counts = self._level_counts(level).astype(np.float64)
            noised_levels.append(
                self._noise_counts(counts, eps_per_level, delta_per_level,
                                   l0_sensitivity, linf_sensitivity,
                                   noise_type))
        return [self._locate_quantile(q, noised_levels) for q in quantiles]

    def _noise_counts(self, counts: np.ndarray, eps: float, delta: float,
                      l0: int, linf: float, noise_type: str) -> np.ndarray:
        if noise_type == "laplace":
            scale = noise_core.laplace_diversity(eps, l0 * linf)
            return counts + noise_core.sample_laplace(scale, counts.shape)
        if noise_type == "gaussian":
            sigma = noise_core.analytic_gaussian_sigma(
                eps, delta, np.sqrt(l0) * linf)
            return counts + noise_core.sample_gaussian(sigma, counts.shape)
        raise ValueError(f"Unknown noise type: {noise_type}")

    def _locate_quantile(self, quantile: float,
                         noised_levels: List[np.ndarray]) -> float:
        """Walks down the tree following the target rank fraction."""
        node = 0  # index at current level
        lo, hi = self._lower, self._upper
        target = quantile
        for level in range(self._height):
            children = np.maximum(
                noised_levels[level][node * self._branching:(node + 1) *
                                     self._branching], 0.0)
            total = children.sum()
            if total <= 0:
                # No signal below this node: return the middle of the range.
                return lo + (hi - lo) / 2
            cumulative = np.cumsum(children)
            rank = target * total
            child = int(np.searchsorted(cumulative, rank, side="right"))
            child = min(child, self._branching - 1)
            below = cumulative[child] - children[child]
            # Fraction of the chosen child's mass below the target.
            target = ((rank - below) /
                      children[child]) if children[child] > 0 else 0.5
            target = min(max(target, 0.0), 1.0)
            width = (hi - lo) / self._branching
            lo = lo + child * width
            hi = lo + width
            node = node * self._branching + child
        return lo + target * (hi - lo)
