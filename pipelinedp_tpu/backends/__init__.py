from pipelinedp_tpu.backends.base import (Annotator, PipelineBackend,
                                          UniqueLabelsGenerator,
                                          register_annotator)
from pipelinedp_tpu.backends.local import LocalBackend, MultiProcLocalBackend

__all__ = [
    "Annotator",
    "LocalBackend",
    "MultiProcLocalBackend",
    "PipelineBackend",
    "UniqueLabelsGenerator",
    "register_annotator",
]
