from pipelinedp_tpu.backends.base import (Annotator, PipelineBackend,
                                          UniqueLabelsGenerator,
                                          register_annotator)
from pipelinedp_tpu.backends.jax_backend import JaxBackend
from pipelinedp_tpu.backends.local import LocalBackend, MultiProcLocalBackend

__all__ = [
    "Annotator",
    "JaxBackend",
    "LocalBackend",
    "MultiProcLocalBackend",
    "PipelineBackend",
    "UniqueLabelsGenerator",
    "register_annotator",
]
