"""Pipeline backend abstraction — the seam between DP logic and execution.

Everything above this layer (combiners, bounders, DPEngine, analysis)
expresses computation exclusively through the ~18 dataflow primitives below,
so an execution strategy (lazy local generators, multiprocess, columnar
JAX/TPU) is a drop-in class.

Parity: pipeline_dp/pipeline_backend.py (PipelineBackend ABC :38-195,
UniqueLabelsGenerator :198-219, Annotator/register_annotator :826-851).
"""

from __future__ import annotations

import abc
import itertools
from typing import Any, Callable, Iterable, List


class PipelineBackend(abc.ABC):
    """Abstract dataflow vocabulary.

    Collections are opaque backend-native handles; all ops are lazy where the
    backend supports it. ``stage_name`` labels the op for explain reports,
    profiles, and debugging.
    """

    def to_collection(self, collection_or_iterable, col, stage_name: str):
        """Converts an iterable to this backend's native collection type.

        ``col`` is an existing native collection used to infer pipeline
        context where needed (e.g. a distributed runtime handle).
        """
        return collection_or_iterable

    def to_multi_transformable_collection(self, col):
        """Returns a collection that supports multiple downstream transforms.

        Needed for generator-based backends where a collection can be
        consumed only once.
        """
        return col

    @abc.abstractmethod
    def map(self, col, fn: Callable, stage_name: str):
        """Element-wise transform."""

    @abc.abstractmethod
    def map_with_side_inputs(self, col, fn: Callable, side_input_cols,
                             stage_name: str):
        """Like map, but fn also receives each side input materialized as a
        list: fn(element, *side_inputs)."""

    @abc.abstractmethod
    def flat_map(self, col, fn: Callable, stage_name: str):
        """Element-wise transform producing zero or more outputs each."""

    def flat_map_with_side_inputs(self, col, fn: Callable, side_input_cols,
                                  stage_name: str):
        """flat_map with side inputs; default via map_with_side_inputs."""
        mapped = self.map_with_side_inputs(col, fn, side_input_cols,
                                           stage_name)
        return self.flat_map(mapped, lambda x: x, f"{stage_name} (flatten)")

    @abc.abstractmethod
    def map_tuple(self, col, fn: Callable, stage_name: str):
        """For collections of tuples: fn(*element)."""

    @abc.abstractmethod
    def map_values(self, col, fn: Callable, stage_name: str):
        """For (key, value) collections: (key, fn(value))."""

    @abc.abstractmethod
    def group_by_key(self, col, stage_name: str):
        """(key, value) -> (key, iterable-of-values). The shuffle."""

    @abc.abstractmethod
    def filter(self, col, fn: Callable, stage_name: str):
        """Keeps elements where fn(element) is truthy."""

    @abc.abstractmethod
    def filter_by_key(self, col, keys_to_keep, stage_name: str):
        """Keeps (key, value) pairs whose key is in keys_to_keep.

        ``keys_to_keep`` may be a local list/set or a backend collection.
        """

    @abc.abstractmethod
    def keys(self, col, stage_name: str):
        """(key, value) -> key."""

    @abc.abstractmethod
    def values(self, col, stage_name: str):
        """(key, value) -> value."""

    @abc.abstractmethod
    def sample_fixed_per_key(self, col, n: int, stage_name: str):
        """(key, value) -> (key, [<=n values sampled without replacement])."""

    @abc.abstractmethod
    def count_per_element(self, col, stage_name: str):
        """element -> (element, multiplicity)."""

    @abc.abstractmethod
    def sum_per_key(self, col, stage_name: str):
        """(key, number) -> (key, sum of numbers)."""

    @abc.abstractmethod
    def combine_accumulators_per_key(self, col, combiner, stage_name: str):
        """(key, accumulator) -> (key, merged accumulator) using
        combiner.merge_accumulators."""

    @abc.abstractmethod
    def reduce_per_key(self, col, fn: Callable, stage_name: str):
        """(key, value) -> (key, reduced value); fn must be associative and
        commutative."""

    @abc.abstractmethod
    def flatten(self, cols: Iterable, stage_name: str):
        """Union of several collections."""

    @abc.abstractmethod
    def distinct(self, col, stage_name: str):
        """Deduplicates the collection."""

    @abc.abstractmethod
    def to_list(self, col, stage_name: str):
        """Collection -> 1-element collection holding a list of all elements."""

    def annotate(self, col, stage_name: str, **kwargs):
        """Applies all registered annotators (no-op unless overridden)."""
        return col


class UniqueLabelsGenerator:
    """Uniquifies stage labels within one pipeline (for legible runtime UIs).

    Parity: pipeline_backend.py:198-219.
    """

    def __init__(self, suffix: str = ""):
        self._labels = set()
        self._suffix = f"_{suffix}" if suffix else ""

    def unique(self, label: str) -> str:
        label = label or "UNDEFINED_STAGE_NAME"
        candidate = label + self._suffix
        if candidate not in self._labels:
            self._labels.add(candidate)
            return candidate
        for i in itertools.count(1):
            candidate = f"{label}_{i}{self._suffix}"
            if candidate not in self._labels:
                self._labels.add(candidate)
                return candidate


class Annotator(abc.ABC):
    """Hook for attaching metadata (e.g. budget) to output collections.

    Parity: pipeline_backend.py:826-851.
    """

    @abc.abstractmethod
    def annotate(self, col, stage_name: str, **kwargs):
        """Returns the annotated collection."""


_annotators: List[Annotator] = []


def register_annotator(annotator: Annotator) -> None:
    _annotators.append(annotator)


def registered_annotators() -> List[Annotator]:
    return list(_annotators)
