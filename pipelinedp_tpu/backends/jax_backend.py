"""JaxBackend: the PipelineBackend with device-accelerated reductions.

Exposes the columnar device kernels through the reference's backend seam
(pipeline_backend.py:38-195) so `DPEngine` graphs — which speak the
map/group/reduce op vocabulary over Python collections — get their per-key
reduction hot-spots (SURVEY.md §3.1: `count_per_element`, `sum_per_key`)
executed as one `segment_sum` on the accelerator instead of a Python dict
loop, with bit-faithful fallback to the host semantics whenever the data
is not numeric-array-friendly.

This is the taxonomy bridge between the two execution styles: the
*columnar engine* (`jax_engine.JaxDPEngine`) is the TPU-first redesign that
bypasses the per-row graph entirely and is what large workloads should
use; `JaxBackend` is for running the *reference-shaped* engine
(`DPEngine`) with device offload, and it passes the same backend
conformance suite as the host backends (tests/pipeline_backend_test.py).
"""

from __future__ import annotations

import operator
import secrets
from typing import Callable

import numpy as np

from pipelinedp_tpu.backends import local
from pipelinedp_tpu.ops import encoding


def _try_columns(pairs):
    """Materializes (key, value) pairs into numeric columns, or None.

    Only plain int keys and int/float scalar values qualify — anything
    else (strings, tuples, accumulator objects) routes to the host path.
    """
    pairs = list(pairs)
    if not pairs:
        return pairs, None, None
    keys, values = [], []
    for pair in pairs:
        if not (isinstance(pair, tuple) and len(pair) == 2):
            return pairs, None, None
        k, v = pair
        if isinstance(k, bool) or not isinstance(k, (int, np.integer)):
            return pairs, None, None
        if isinstance(v, bool) or not isinstance(
                v, (int, float, np.integer, np.floating)):
            return pairs, None, None
        keys.append(k)
        values.append(v)
    try:
        keys_arr = np.asarray(keys, dtype=np.int64)
        values_arr = np.asarray(values)
    except OverflowError:
        # Arbitrary-precision Python ints beyond int64: host path only.
        return pairs, None, None
    if values_arr.dtype == object or values_arr.dtype == np.uint64:
        return pairs, None, None
    return pairs, keys_arr, values_arr


class JaxBackend(local.LocalBackend):
    """LocalBackend semantics; numeric per-key reductions and the per-key
    sampling hot-spot on the device."""

    # sample_fixed_per_key engages the device kernel above this many pairs
    # (below it, the kernel launch costs more than the host loop). Class
    # attribute so tests can force the device path on small data.
    SAMPLE_DEVICE_MIN_ROWS = 1 << 15

    def sum_per_key(self, col, stage_name: str = None):

        def gen():
            pairs, keys, values = _try_columns(col)
            if keys is None:
                yield from local.LocalBackend.reduce_per_key(
                    self, pairs, operator.add, stage_name)
                return
            yield from self._segment_reduce(keys, values)

        return gen()

    def reduce_per_key(self, col, fn: Callable, stage_name: str = None):
        """Host reduce with device offload for the recognizable numeric
        reductions (operator.add, builtin min/max).

        Arbitrary fns keep LocalBackend's arrival-order fold — a general
        callable can be non-commutative, which a segment reduction must
        not reorder."""
        if fn is operator.add:
            return self.sum_per_key(col, stage_name)
        if fn is min or fn is max:

            def gen():
                pairs, keys, values = _try_columns(col)
                if keys is None:
                    yield from local.LocalBackend.reduce_per_key(
                        self, pairs, fn, stage_name)
                    return
                yield from self._segment_extremum(keys, values, fn is min)

            return gen()
        return local.LocalBackend.reduce_per_key(self, col, fn, stage_name)

    def sample_fixed_per_key(self, col, n: int, stage_name: str = None):
        """Uniform sample of at most n values per key.

        The sampling decision depends only on the keys, so the device
        kernel (columnar.bound_row_mask with the key as the privacy id and
        a single pseudo-partition: rank-below-n inside one random-tiebreak
        sort — contribution_bounders.py:62-111 semantics) computes the
        keep mask for any value type; values never leave the host. This
        is the §3.1 `sample_fixed_per_key` hot spot of the reference
        graph."""

        def gen():
            pairs = list(col)
            if (len(pairs) < self.SAMPLE_DEVICE_MIN_ROWS or not all(
                    isinstance(p, tuple) and len(p) == 2 for p in pairs)):
                yield from local.LocalBackend.sample_fixed_per_key(
                    self, pairs, n, stage_name)
                return
            try:
                keys_arr = np.asarray([k for k, _ in pairs])
                if keys_arr.dtype == object or keys_arr.ndim != 1:
                    # Mixed-type or composite (tuple) keys: host path.
                    raise TypeError("non-scalar keys")
                ids, uniques = encoding._factorize(keys_arr)
            except (TypeError, ValueError):
                yield from local.LocalBackend.sample_fixed_per_key(
                    self, pairs, n, stage_name)
                return
            import jax
            import jax.numpy as jnp
            from pipelinedp_tpu.ops import columnar
            # Sampling keeps/drops user contributions, so the key must not
            # be predictable: seed from the OS CSPRNG, not np.random.
            prng = jax.random.PRNGKey(secrets.randbits(31))
            mask = np.asarray(
                columnar.bound_row_mask(
                    prng, jnp.asarray(ids),
                    jnp.zeros(len(ids), dtype=jnp.int32),
                    jnp.ones(len(ids), dtype=bool), n, 1))
            kept: dict = {}
            for keep, (k, v) in zip(mask, pairs):
                if keep:
                    kept.setdefault(k, []).append(v)
            yield from kept.items()

        return gen()

    @staticmethod
    def _segment_extremum(keys: np.ndarray, values: np.ndarray,
                          is_min: bool):
        """Per-key min/max on device. Exact for int32-range ints and all
        floats (extrema never overflow); wider ints reduce on host."""
        ids, uniques = encoding._factorize(keys)
        int_values = np.issubdtype(values.dtype, np.integer)
        fits_i32 = (int_values and len(values) > 0 and
                    np.iinfo(np.int32).min <= values.min() and
                    values.max() <= np.iinfo(np.int32).max)
        if fits_i32 or not int_values:
            import jax
            import jax.numpy as jnp
            op = jax.ops.segment_min if is_min else jax.ops.segment_max
            dtype = jnp.int32 if int_values else jnp.float32
            if not int_values and values.dtype == np.float64:
                # float64 inputs reduce on host (device is f32).
                out = np.full(len(uniques), np.inf if is_min else -np.inf)
                (np.minimum if is_min else np.maximum).at(out, ids, values)
            else:
                out = jax.device_get(
                    op(jnp.asarray(values, dtype=dtype), jnp.asarray(ids),
                       num_segments=len(uniques)))
        else:
            out = np.full(len(uniques),
                          np.iinfo(np.int64).max if is_min else
                          np.iinfo(np.int64).min,
                          dtype=np.int64)
            (np.minimum if is_min else np.maximum).at(out, ids, values)
        for key, v in zip(uniques, out):
            yield int(key), (int(v) if int_values else float(v))

    def count_per_element(self, col, stage_name: str = None):

        def gen():
            elements = list(col)
            if not all(
                    isinstance(x, (int, np.integer)) and
                    not isinstance(x, bool) for x in elements):
                yield from local.LocalBackend.count_per_element(
                    self, elements, stage_name)
                return
            try:
                keys = np.asarray(elements, dtype=np.int64)
            except OverflowError:
                yield from local.LocalBackend.count_per_element(
                    self, elements, stage_name)
                return
            # int64 ones so counting takes the device int32 path.
            for key, total in self._segment_reduce(
                    keys, np.ones(len(keys), dtype=np.int64)):
                yield key, int(total)

        return gen()

    @staticmethod
    def _segment_reduce(keys: np.ndarray, values: np.ndarray):
        """Segment sum over dictionary-encoded keys — exactness first.

        The device path runs int32, so it engages only when the total
        absolute mass provably fits (no silent wraparound). Larger integer
        inputs reduce exactly on host (int64 np.add.at, escalating to
        Python ints when int64 could overflow) — bit-faithful to
        LocalBackend's Python-int reduction at any magnitude. Floats take
        the vectorized float64 bincount.
        """
        ids, uniques = encoding._factorize(keys)
        int_values = np.issubdtype(values.dtype, np.integer)
        # Magnitude check in float64 (abs of int64-min would wrap); the
        # 2^16 margin covers float64 rounding of the sum.
        device_safe = (int_values and len(values) > 0 and
                       float(np.abs(values.astype(np.float64)).sum()) <
                       float(np.iinfo(np.int32).max - (1 << 16)))
        if device_safe:
            import jax
            import jax.numpy as jnp
            sums = jax.device_get(
                jax.ops.segment_sum(jnp.asarray(values, dtype=jnp.int32),
                                    jnp.asarray(ids),
                                    num_segments=len(uniques)))
        elif int_values:
            # Hot integers too big for int32 on device: exact int64
            # accumulation with the overflow check numpy won't do itself;
            # arbitrary-precision Python ints on detected risk. float64
            # bincount would silently lose exactness past 2^53.
            sums = np.zeros(len(uniques), dtype=np.int64)
            # Python-int abs: np.abs(int64 min) would wrap.
            max_abs = (max(abs(int(values.max())), abs(int(values.min())))
                       if len(values) else 0)
            if max_abs and len(values) > (2**62) // max_abs:
                totals = [0] * len(uniques)
                for i, v in zip(ids, values):
                    totals[i] += int(v)
                sums = totals
            else:
                np.add.at(sums, ids, values.astype(np.int64))
        else:
            sums = np.bincount(ids,
                               weights=values.astype(np.float64),
                               minlength=len(uniques))
        for key, total in zip(uniques, sums):
            yield int(key), (int(total) if int_values else float(total))
