"""Single-process backends.

``LocalBackend`` is the lazy-generator execution strategy: every op returns a
generator, nothing runs until the output is iterated. It is the correctness
oracle the JAX backend is conformance-tested against, and the CPU baseline
for the benchmark targets.

Parity: pipeline_dp/pipeline_backend.py LocalBackend :477-583 (lazy
generators, defaultdict group-by), MultiProcLocalBackend :600-823
(experimental multi-worker local execution).
"""

from __future__ import annotations

import collections
import concurrent.futures
import itertools
import logging
import multiprocessing
import operator
import sys
from typing import Callable, Iterable

from pipelinedp_tpu.backends import base
from pipelinedp_tpu.sampling_utils import choose_from_list_without_replacement


class LocalBackend(base.PipelineBackend):
    """Lazy single-process backend over Python iterables."""

    def to_collection(self, collection_or_iterable, col, stage_name: str):
        return collection_or_iterable

    def to_multi_transformable_collection(self, col):
        return list(col)

    def map(self, col, fn: Callable, stage_name: str = None):
        return (fn(x) for x in col)

    def map_with_side_inputs(self, col, fn: Callable, side_input_cols,
                             stage_name: str = None):

        def gen():
            side_inputs = [list(s) for s in side_input_cols]
            for x in col:
                yield fn(x, *side_inputs)

        return gen()

    def flat_map(self, col, fn: Callable, stage_name: str = None):
        return (y for x in col for y in fn(x))

    def flat_map_with_side_inputs(self, col, fn: Callable, side_input_cols,
                                  stage_name: str = None):

        def gen():
            side_inputs = [list(s) for s in side_input_cols]
            for x in col:
                yield from fn(x, *side_inputs)

        return gen()

    def map_tuple(self, col, fn: Callable, stage_name: str = None):
        return (fn(*x) for x in col)

    def map_values(self, col, fn: Callable, stage_name: str = None):
        return ((k, fn(v)) for k, v in col)

    def group_by_key(self, col, stage_name: str = None):

        def gen():
            groups = collections.defaultdict(list)
            for key, value in col:
                groups[key].append(value)
            yield from groups.items()

        return gen()

    def filter(self, col, fn: Callable, stage_name: str = None):
        return (x for x in col if fn(x))

    def filter_by_key(self, col, keys_to_keep, stage_name: str = None):

        def gen():
            keep = keys_to_keep
            if not isinstance(keep, (list, set, frozenset, dict)):
                keep = list(keep)
            keep = set(keep) if not isinstance(keep, (set, frozenset)) else keep
            for key, value in col:
                if key in keep:
                    yield key, value

        return gen()

    def keys(self, col, stage_name: str = None):
        return (k for k, _ in col)

    def values(self, col, stage_name: str = None):
        return (v for _, v in col)

    def sample_fixed_per_key(self, col, n: int, stage_name: str = None):
        grouped = self.group_by_key(col, stage_name)
        return ((k, choose_from_list_without_replacement(v, n))
                for k, v in grouped)

    def count_per_element(self, col, stage_name: str = None):

        def gen():
            counts = collections.Counter(col)
            yield from counts.items()

        return gen()

    def sum_per_key(self, col, stage_name: str = None):
        # operator.add: picklable by reference, unlike a lambda (the
        # multiprocess backend's 'processes' mode ships it to workers).
        return self.reduce_per_key(col, operator.add, stage_name)

    def combine_accumulators_per_key(self, col, combiner,
                                     stage_name: str = None):
        return self.reduce_per_key(col, combiner.merge_accumulators,
                                   stage_name)

    def reduce_per_key(self, col, fn: Callable, stage_name: str = None):

        def gen():
            reduced = {}
            for key, value in col:
                if key in reduced:
                    reduced[key] = fn(reduced[key], value)
                else:
                    reduced[key] = value
            yield from reduced.items()

        return gen()

    def flatten(self, cols: Iterable, stage_name: str = None):
        return itertools.chain(*cols)

    def distinct(self, col, stage_name: str = None):

        def gen():
            yield from set(col)

        return gen()

    def to_list(self, col, stage_name: str = None):
        return iter([list(col)])

    def annotate(self, col, stage_name: str = None, **kwargs):
        for annotator in base.registered_annotators():
            col = annotator.annotate(col, stage_name, **kwargs)
        return col


class MultiProcLocalBackend(LocalBackend):
    """Experimental multi-worker local backend.

    Parallelizes the element-wise ops (map / flat_map / filter) across a
    worker pool while inheriting the shuffle ops from LocalBackend. Because
    arbitrary Python closures are not picklable, workers are threads by
    default ("threads" mode); "processes" mode uses a process pool and
    requires picklable functions. The reference's equivalent
    (pipeline_backend.py:600-823) is likewise marked experimental with
    several ops unimplemented.
    """

    def __init__(self, n_jobs: int = None, mode: str = "threads",
                 chunksize: int = 1024):
        self._n_jobs = n_jobs or multiprocessing.cpu_count()
        if mode not in ("threads", "processes"):
            raise ValueError(f"mode must be 'threads' or 'processes': {mode}")
        self._mode = mode
        self._chunksize = chunksize
        self._warned_fork_after_jax = False

    def _executor(self):
        if self._mode == "threads":
            return concurrent.futures.ThreadPoolExecutor(self._n_jobs)
        # Platform-default start method (fork on Linux), like the
        # reference's multiprocessing.Pool: spawn would re-import
        # __main__, breaking stdin scripts and notebooks. Forking a
        # JAX-initialized (multithreaded) parent can deadlock the child,
        # so warn loudly when that combination is detected; prefer
        # "threads" mode unless the workload is CPU-bound Python.
        if "jax" in sys.modules and not self._warned_fork_after_jax:
            self._warned_fork_after_jax = True
            logging.warning(
                "MultiProcLocalBackend 'processes' mode forks after JAX "
                "initialization; forked children of a multithreaded parent "
                "can deadlock. Use mode='threads', or build the pipeline "
                "before importing jax.")
        return concurrent.futures.ProcessPoolExecutor(self._n_jobs)

    def _parallel_chunks(self, col, chunk_fn: Callable):
        # Keeps at most 2 * n_jobs chunks in flight so a large (or streamed)
        # input is never materialized whole — Executor.map would consume the
        # entire chunk iterator eagerly.

        def gen():
            for result in self._chunk_results(col, chunk_fn):
                yield from result

        return gen()

    def map(self, col, fn: Callable, stage_name: str = None):
        return self._parallel_chunks(col, _MapChunk(fn))

    def flat_map(self, col, fn: Callable, stage_name: str = None):
        return self._parallel_chunks(col, _FlatMapChunk(fn))

    def filter(self, col, fn: Callable, stage_name: str = None):
        return self._parallel_chunks(col, _FilterChunk(fn))

    def map_tuple(self, col, fn: Callable, stage_name: str = None):
        return self._parallel_chunks(col, _MapTupleChunk(fn))

    def map_values(self, col, fn: Callable, stage_name: str = None):
        return self._parallel_chunks(col, _MapValuesChunk(fn))

    def reduce_per_key(self, col, fn: Callable, stage_name: str = None):
        """Parallel per-key reduce: workers reduce chunks to partial dicts,
        the main thread merges the partials with the same fn.

        This is the shuffle/reduce hot-spot of the aggregation graph
        (combine_accumulators_per_key / sum_per_key route here) — the one
        op the reference's experimental multiproc backend left serial.
        Associativity of fn is already required by the Combiner contract.
        """

        def gen():
            merged = {}
            for partial in self._chunk_results(col, _ReduceChunk(fn)):
                if not merged:
                    merged = partial
                    continue
                for key, value in partial.items():
                    if key in merged:
                        merged[key] = fn(merged[key], value)
                    else:
                        merged[key] = value
            yield from merged.items()

        return gen()

    def _chunk_results(self, col, chunk_fn: Callable):
        """Yields one result object per processed chunk (no flattening)."""
        iter_col = iter(col)
        chunks = iter(
            lambda: list(itertools.islice(iter_col, self._chunksize)), [])
        max_in_flight = 2 * self._n_jobs
        with self._executor() as pool:
            in_flight = collections.deque()
            for chunk in chunks:
                in_flight.append(pool.submit(chunk_fn, chunk))
                if len(in_flight) >= max_in_flight:
                    yield in_flight.popleft().result()
            while in_flight:
                yield in_flight.popleft().result()


class _MapChunk:

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, chunk):
        return [self._fn(x) for x in chunk]


class _FlatMapChunk:

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, chunk):
        return [y for x in chunk for y in self._fn(x)]


class _FilterChunk:

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, chunk):
        return [x for x in chunk if self._fn(x)]


class _MapTupleChunk:

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, chunk):
        return [self._fn(*x) for x in chunk]


class _MapValuesChunk:

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, chunk):
        return [(k, self._fn(v)) for k, v in chunk]


class _ReduceChunk:
    """Per-chunk partial reduce to a {key: reduced_value} dict."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, chunk):
        reduced = {}
        for key, value in chunk:
            if key in reduced:
                reduced[key] = self._fn(reduced[key], value)
            else:
                reduced[key] = value
        return reduced
