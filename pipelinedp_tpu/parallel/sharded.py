"""Multi-chip execution: shard_map over a 2D ('dp', 'mp') device mesh.

This is the TPU-native replacement for the reference's distributed shuffle
(Beam runner / Spark shuffle behind group_by_key and
combine_accumulators_per_key, pipeline_backend.py:223-474; SURVEY.md §2.5):

  * rows are sharded over all mesh devices (data parallelism across both
    axes) — the host loader hash-shards rows by privacy id, so each privacy
    unit's rows are local to one device and contribution bounding is exact
    without any cross-device exchange;
  * each device runs the fused bound-and-aggregate kernel on its shard,
    producing per-partition partial accumulators [padded_p];
  * partials are combined with `psum_scatter` over 'dp' then 'mp' — the
    reduce-scatter rides ICI and leaves every device holding the *full* sum
    for a distinct 1/(dp*mp) slice of the partition space (this is the
    shuffle);
  * the returned accumulators are global jax.Arrays sharded over the
    partition dimension, so everything downstream — partition selection,
    per-mechanism noise, metric math — runs sharded too under XLA's SPMD
    partitioner without further shard_map plumbing.

JaxDPEngine(mesh=...) routes its fused kernel through here; every metric,
selection strategy, and noise mechanism the engine supports works on any
mesh shape unchanged. __graft_entry__.dryrun_multichip exercises the full
engine path on a virtual CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pipelinedp_tpu.ops import columnar
from pipelinedp_tpu.ops import quantiles as quantile_ops
from pipelinedp_tpu.runtime import driver as driver_lib


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map with a fallback for older JAX releases, where it
    lives in jax.experimental.shard_map and the replication-check flag is
    named check_rep."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def _spec(mesh: Mesh) -> P:
    """Row arrays shard over every mesh axis (dcn included)."""
    return P(tuple(mesh.axis_names))


def _scatter_axes(mesh: Mesh) -> tuple:
    """Reduce-scatter order: ICI axes first, 'dcn' last, so the partials
    crossing the slow inter-slice links are already reduced within each
    slice (payload shrinks by dp*mp before touching DCN)."""
    axes = tuple(a for a in mesh.axis_names if a != "dcn")
    if "dcn" in mesh.axis_names:
        axes += ("dcn",)
    return axes


def _part_spec(mesh: Mesh) -> P:
    """Partition-dimension layout after the reduce-scatter: must list the
    axes in scatter order for the chunks to assemble correctly."""
    return P(_scatter_axes(mesh))


def make_mesh(n_devices: Optional[int] = None,
              dp: Optional[int] = None,
              mp: Optional[int] = None,
              devices=None,
              n_slices: int = 1) -> Mesh:
    """Builds a ('dp', 'mp') mesh — or ('dcn', 'dp', 'mp') with n_slices>1
    — over the available devices.

    Default factorization puts the larger factor on 'dp' (rows usually
    outnumber partitions per device). The 'dcn' axis models multi-slice /
    multi-host deployments: devices within a slice talk over ICI, slices
    over DCN, and the reduce-scatter runs intra-slice first so only
    already-reduced partition partials cross the slow links.
    """
    if devices is None:
        devices = jax.devices()
    n = n_devices or len(devices)
    if n_slices > 1 and n % n_slices != 0:
        raise ValueError(f"n_devices={n} not divisible by "
                         f"n_slices={n_slices}")
    per_slice = n // n_slices
    if dp is None or mp is None:
        mp = 1
        for candidate in range(int(np.sqrt(per_slice)), 0, -1):
            if per_slice % candidate == 0:
                mp = candidate
                break
        dp = per_slice // mp
    if dp * mp != per_slice:
        raise ValueError(f"dp*mp={dp*mp} != devices per slice={per_slice}")
    if n_slices > 1:
        return Mesh(
            np.asarray(devices[:n]).reshape(n_slices, dp, mp),
            ("dcn", "dp", "mp"))
    return Mesh(np.asarray(devices[:n]).reshape(dp, mp), ("dp", "mp"))


def padded_num_partitions(mesh: Mesh, num_partitions: int) -> int:
    """num_partitions rounded up so the partition dim shards evenly."""
    n_dev = mesh.devices.size
    return ((num_partitions + n_dev - 1) // n_dev) * n_dev


def shard_rows_by_pid(pid: np.ndarray,
                      pk: np.ndarray,
                      value: np.ndarray,
                      n_shards: int,
                      valid: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
    """Host-side loader step: hash-shard rows by privacy id and pad shards
    to equal length.

    Returns arrays of shape [n_shards * shard_len] laid out shard-major,
    plus the validity mask for padding rows. Keeping each pid on one shard
    makes L0/Linf bounding exact with zero cross-device row exchange.
    """
    # Multiplicative hash, not bare modulo: raw (unfactorized) id spaces
    # are often structured (all-even ids, per-site ranges) and would skew
    # a low-bits split, doubling shard padding.
    hashed = ((pid.astype(np.uint32) * np.uint32(2654435761)) >>
              np.uint32(16))
    shard_of_row = hashed % np.uint32(n_shards)
    order = np.argsort(shard_of_row, kind="stable")
    pid, pk, value = pid[order], pk[order], value[order]
    valid = (np.ones(len(pid), dtype=bool)
             if valid is None else np.asarray(valid)[order])
    shard_of_row = shard_of_row[order]
    counts = np.bincount(shard_of_row, minlength=n_shards)
    shard_len = int(counts.max()) if len(pid) else 1
    total = n_shards * shard_len
    out_pid = np.zeros(total, dtype=pid.dtype)
    out_pk = np.zeros(total, dtype=pk.dtype)
    out_val = np.zeros((total,) + value.shape[1:], dtype=value.dtype)
    out_valid = np.zeros(total, dtype=bool)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    for s in range(n_shards):
        lo, n_rows = offsets[s], counts[s]
        dst = s * shard_len
        out_pid[dst:dst + n_rows] = pid[lo:lo + n_rows]
        out_pk[dst:dst + n_rows] = pk[lo:lo + n_rows]
        out_val[dst:dst + n_rows] = value[lo:lo + n_rows]
        out_valid[dst:dst + n_rows] = valid[lo:lo + n_rows]
    return out_pid, out_pk, out_val, out_valid


def _device_key(key, axes):
    """Independent PRNG stream per mesh position."""
    for axis in axes:
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
    return key


def _reduce_scatter(x, scatter_axes):
    # Scatter in _scatter_axes order (ICI first, DCN last): each hop moves
    # already-partially-reduced data, and the chunk each device ends up
    # holding matches the _part_spec output layout.
    for axis in scatter_axes:
        x = jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    return x


@functools.lru_cache(maxsize=None)
def _scalar_kernel(mesh: Mesh, padded_p: int, has_l1: bool = False,
                   need_flags=(True, True, True, True),
                   has_group_clip: bool = True):
    """Sharded twin of columnar.bound_and_aggregate for a given mesh.

    has_l1 compiles the max_contributions variant (an extra runtime l1_cap
    scalar and the per-pid total sample in the local kernel) — shards are
    pid-disjoint, so per-shard L1 sampling is exact.
    """

    axes = tuple(mesh.axis_names)
    scatter = _scatter_axes(mesh)

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, row_clip_lo,
                   row_clip_hi, middle, group_clip_lo, group_clip_hi,
                   *l1_args):
        accs = columnar.bound_and_aggregate(
            _device_key(key, axes), pid, pk, value, valid,
            num_partitions=padded_p,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi,
            middle=middle,
            group_clip_lo=group_clip_lo,
            group_clip_hi=group_clip_hi,
            l1_cap=l1_args[0] if has_l1 else None,
            need_count=need_flags[0],
            need_sum=need_flags[1],
            need_norm=need_flags[2],
            need_norm_sq=need_flags[3],
            has_group_clip=has_group_clip)
        return jax.tree.map(lambda x: _reduce_scatter(x, scatter), accs)

    spec = _spec(mesh)
    part = _part_spec(mesh)
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + (spec,) * 4 + (P(),) * (8 if has_l1 else 7),
        out_specs=columnar.PartitionAccumulators(*([part] * 5)),
        check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _vector_kernel(mesh: Mesh, padded_p: int, norm_ord: int,
                   has_l1: bool = False, pid_sorted: bool = False,
                   max_segments=None):
    """Sharded twin of columnar.bound_and_aggregate_vector.

    pid_sorted: every device's local block is pid-nondecreasing over its
    valid prefix (the host pre-sorted rows by pid before the stable
    shard partition of shard_rows_by_pid, which preserves in-shard
    order), so the local sampler runs the packed 3-key sort shared with
    the scalar path; max_segments bounds the distinct pids of any one
    shard (the global distinct-pid count is always valid)."""

    axes = tuple(mesh.axis_names)
    scatter = _scatter_axes(mesh)

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, max_norm,
                   *l1_args):
        vector_sums, accs = columnar.bound_and_aggregate_vector(
            _device_key(key, axes), pid, pk, value, valid,
            num_partitions=padded_p,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            max_norm=max_norm,
            norm_ord=norm_ord,
            l1_cap=l1_args[0] if has_l1 else None,
            pid_sorted=pid_sorted,
            max_segments=max_segments)
        return (_reduce_scatter(vector_sums, scatter),
                jax.tree.map(lambda x: _reduce_scatter(x, scatter), accs))

    spec = _spec(mesh)
    part = _part_spec(mesh)
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + (spec,) * 4 + (P(),) * (4 if has_l1 else 3),
        out_specs=(part,
                   columnar.PartitionAccumulators(*([part] * 5))),
        check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _quantile_kernel(mesh: Mesh, padded_p: int, num_leaves: int,
                     has_l1: bool = False):
    """Sharded leaf-histogram kernel for the batched quantile trees."""

    axes = tuple(mesh.axis_names)
    scatter = _scatter_axes(mesh)

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, lower,
                   upper, *l1_args):
        mask = columnar.bound_row_mask(_device_key(key, axes), pid, pk,
                                       valid, linf_cap, l0_cap,
                                       l1_cap=l1_args[0] if has_l1 else None)
        hist = quantile_ops.leaf_histograms(pk, value, mask,
                                            num_partitions=padded_p,
                                            num_leaves=num_leaves,
                                            lower=lower,
                                            upper=upper)
        return _reduce_scatter(hist, scatter)

    spec = _spec(mesh)
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + (spec,) * 4 + (P(),) * (5 if has_l1 else 4),
        out_specs=_part_spec(mesh),
        check_vma=False)
    return jax.jit(fn)


def quantile_leaf_histograms(mesh: Mesh, key, pid, pk, value, valid, *,
                             num_partitions: int, num_leaves: int, lower,
                             upper, linf_cap, l0_cap, l1_cap=None):
    """Multi-chip [padded_p, num_leaves] quantile-tree leaf counts."""
    padded_p = padded_num_partitions(mesh, num_partitions)
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    kernel = _quantile_kernel(mesh, padded_p, num_leaves,
                              has_l1=l1_cap is not None)
    args = (key, dpid, dpk, dval, dvalid, linf_cap, l0_cap, float(lower),
            float(upper))
    if l1_cap is not None:
        args += (l1_cap,)
    return kernel(*args)


def host_row_mask(mesh: Mesh, key, pid, pk, *, linf_cap, l0_cap,
                  l1_cap=None) -> np.ndarray:
    """Contribution-bounding keep mask for host rows, computed on the mesh.

    The custom-combiner path under mesh=: rows are hash-sharded by privacy
    id (pid-disjoint shards make Linf/L0/L1 sampling per shard exact —
    same argument as ops/streaming.py), the sharded row-mask kernel runs
    on every device, and the mask comes back scattered to the caller's row
    order. Only the two id columns ship; the value column stays on host so
    user combiners keep exact float64 inputs (reference behavior: custom
    combiners run on every backend, combiners.py:925).
    """
    pid = np.asarray(pid)
    pk = np.asarray(pk, dtype=np.int32)
    n = len(pid)
    if n == 0:
        return np.zeros(0, dtype=bool)
    n_dev = mesh.devices.size
    hashed = ((pid.astype(np.uint32) * np.uint32(2654435761)) >>
              np.uint32(16))
    shard_of_row = hashed % np.uint32(n_dev)
    order = np.argsort(shard_of_row, kind="stable")
    counts = np.bincount(shard_of_row, minlength=n_dev)
    shard_len = int(counts.max())
    total = n_dev * shard_len
    spid = np.zeros(total, dtype=np.int32)
    spk = np.zeros(total, dtype=np.int32)
    svalid = np.zeros(total, dtype=bool)
    # staged slot -> original row (for the scatter back).
    src = np.zeros(total, dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    for s in range(n_dev):
        lo, m = offsets[s], counts[s]
        dst = s * shard_len
        rows = order[lo:lo + m]
        spid[dst:dst + m] = pid[rows]
        spk[dst:dst + m] = pk[rows]
        svalid[dst:dst + m] = True
        src[dst:dst + m] = rows
    sharding = NamedSharding(mesh, _spec(mesh))
    dpid, dpk, dvalid = (jax.device_put(a, sharding)
                         for a in (spid, spk, svalid))
    kernel = _row_mask_kernel(mesh, has_l1=l1_cap is not None)
    args = (key, dpid, dpk, dvalid, linf_cap, l0_cap)
    if l1_cap is not None:
        args += (l1_cap,)
    staged_mask = np.asarray(kernel(*args))
    out = np.zeros(n, dtype=bool)
    out[src[svalid]] = staged_mask[svalid]
    return out


@functools.lru_cache(maxsize=None)
def _row_mask_kernel(mesh: Mesh, has_l1: bool = False):
    """Sharded contribution-bounding row mask (row-sharded in and out).

    One sampling pass shared by every partition block of the blocked
    quantile path — the expensive per-device sorts run once, not once per
    block."""

    axes = tuple(mesh.axis_names)

    def local_step(key, pid, pk, valid, linf_cap, l0_cap, *l1_args):
        return columnar.bound_row_mask(_device_key(key, axes), pid, pk,
                                       valid, linf_cap, l0_cap,
                                       l1_cap=l1_args[0] if has_l1 else None)

    spec = _spec(mesh)
    fn = shard_map(local_step,
                       mesh=mesh,
                       in_specs=(P(),) + (spec,) * 3 + (P(),) *
                       (3 if has_l1 else 2),
                       out_specs=spec,
                       check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _local_pk_sort_kernel(mesh: Mesh):
    """Sorts each device's rows by partition id (one argsort + gathers) so
    the per-block kernels can window a contiguous row range instead of
    rescanning every row for every block."""

    def local_step(pk, value, mask):
        order = jnp.argsort(pk)
        return pk[order], value[order], mask[order]

    spec = _spec(mesh)
    fn = shard_map(local_step,
                       mesh=mesh,
                       in_specs=(spec,) * 3,
                       out_specs=(spec,) * 3,
                       check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _block_rows_cap_kernel(mesh: Mesh, block_p: int, n_blocks: int):
    """Max rows any device holds for any partition block (replicated
    scalar) — the static window size of the block-histogram kernel."""

    axes = tuple(mesh.axis_names)

    def local_step(spk, mask):
        block_of_row = jnp.minimum(spk // block_p, n_blocks - 1)
        counts = jax.ops.segment_sum(mask.astype(jnp.int32), block_of_row,
                                     num_segments=n_blocks,
                                     indices_are_sorted=True)
        m = counts.max()
        for axis in axes:
            m = jax.lax.pmax(m, axis)
        return m

    spec = _spec(mesh)
    fn = shard_map(local_step,
                       mesh=mesh,
                       in_specs=(spec, spec),
                       out_specs=P(),
                       check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _block_hist_kernel(mesh: Mesh, block_p: int, num_leaves: int,
                       window: int):
    """Sharded [block_p, num_leaves] leaf histogram of one partition block
    [p0, p0 + block_p) over pk-sorted local rows: each device slices the
    `window` rows starting at its block boundary (searchsorted), so a
    block's cost is proportional to the window, not the full row set."""

    scatter = _scatter_axes(mesh)

    def local_step(spk, value, mask, p0, lower, upper):
        n_local = spk.shape[0]
        start = jnp.searchsorted(spk, p0).astype(jnp.int32)
        start = jnp.clip(start, 0, max(n_local - window, 0))
        wpk = jax.lax.dynamic_slice_in_dim(spk, start, window)
        wval = jax.lax.dynamic_slice_in_dim(value, start, window)
        wmask = jax.lax.dynamic_slice_in_dim(mask, start, window)
        in_block = wmask & (wpk >= p0) & (wpk < p0 + block_p)
        local_pk = jnp.clip(wpk - p0, 0, block_p - 1)
        hist = quantile_ops.leaf_histograms(local_pk, wval, in_block,
                                            num_partitions=block_p,
                                            num_leaves=num_leaves,
                                            lower=lower,
                                            upper=upper)
        return _reduce_scatter(hist, scatter)

    spec = _spec(mesh)
    fn = shard_map(local_step,
                       mesh=mesh,
                       in_specs=(spec,) * 3 + (P(),) * 3,
                       out_specs=_part_spec(mesh),
                       check_vma=False)
    return jax.jit(fn)


def blocked_quantile_columns(mesh: Mesh, key, pid, pk, value, valid, *,
                             num_partitions: int, num_leaves: int, lower,
                             upper, linf_cap, l0_cap, num_quantiles: int,
                             finish_fn, l1_cap=None) -> np.ndarray:
    """[num_partitions, num_quantiles] DP quantiles on the mesh, blocked.

    Mesh twin of ops/quantiles.blocked_quantile_columns for partition
    counts whose dense [partitions, leaves] layout exceeds the device
    budget: the contribution-bounding mask is computed once (sharded), each
    device sorts its rows by pk once, and each partition block histograms
    only a contiguous row window (searchsorted + dynamic slice, padded to
    the max per-device block population so one kernel serves every block).
    The [block_p, num_leaves] result feeds finish_fn (noise + tree walk) —
    identical released values to the dense path, bounded memory. The
    eps/delta split is per tree, so per-block noising composes exactly.
    """
    n_dev = mesh.devices.size
    block_p = max(1, quantile_ops.MAX_HISTOGRAM_ELEMENTS // num_leaves)
    block_p = max(n_dev, (block_p // n_dev) * n_dev)
    n_blocks = (num_partitions + block_p - 1) // block_p
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    mask_kernel = _row_mask_kernel(mesh, has_l1=l1_cap is not None)
    args = (key, dpid, dpk, dvalid, linf_cap, l0_cap)
    if l1_cap is not None:
        args += (l1_cap,)
    mask = mask_kernel(*args)
    spk, sval, smask = _local_pk_sort_kernel(mesh)(dpk, dval, mask)
    n_local = int(np.asarray(dpk.shape[0])) // n_dev
    # Window = max per-device rows in any block, rounded up to a power of
    # two (few compiled shapes); counting masked-out rows too keeps the
    # window an upper bound on any block's slice.
    cap = int(
        _block_rows_cap_kernel(mesh, block_p, n_blocks)(
            spk, jnp.ones_like(smask)))
    window = 1 << max(cap - 1, 0).bit_length()
    window = int(min(max(window, 1024), max(n_local, 1)))
    hist_kernel = _block_hist_kernel(mesh, block_p, num_leaves, window)
    out = np.zeros((num_partitions, num_quantiles), dtype=np.float64)
    for p0 in range(0, num_partitions, block_p):
        p1 = min(p0 + block_p, num_partitions)
        hist = hist_kernel(spk, sval, smask, p0, float(lower), float(upper))
        out[p0:p1] = np.asarray(finish_fn(hist))[:p1 - p0]
    return out


def _shard_and_put(mesh: Mesh, pid, pk, value, valid):
    """Stages host rows onto the mesh; passes through already-staged
    jax.Arrays so callers running several kernels over the same rows (e.g.
    aggregate + quantile histogram) pay the host shuffle and transfer once.
    """
    if isinstance(pid, jax.Array):
        return pid, pk, value, valid
    n_dev = mesh.devices.size
    spid, spk, sval, svalid = shard_rows_by_pid(np.asarray(pid),
                                                np.asarray(pk),
                                                np.asarray(value), n_dev,
                                                np.asarray(valid))
    sharding = NamedSharding(mesh, _spec(mesh))
    return tuple(
        jax.device_put(a, sharding) for a in (spid, spk, sval, svalid))


def stage_rows(mesh: Mesh, pid, pk, value, valid):
    """Public staging step: hash-shard + device_put once, reuse across
    kernels."""
    return _shard_and_put(mesh, pid, pk, value, valid)


def bound_and_aggregate(mesh: Mesh,
                        key: jax.Array,
                        pid: np.ndarray,
                        pk: np.ndarray,
                        value: np.ndarray,
                        valid: np.ndarray,
                        *,
                        num_partitions: int,
                        linf_cap,
                        l0_cap,
                        row_clip_lo,
                        row_clip_hi,
                        middle,
                        group_clip_lo,
                        group_clip_hi,
                        l1_cap=None,
                        need_flags=(True, True, True, True),
                        has_group_clip: bool = True
                        ) -> columnar.PartitionAccumulators:
    """Multi-chip bound-and-aggregate: host rows in, global sharded
    [padded_p] accumulators out (padding partitions are all-zero; callers
    trim to num_partitions when materializing)."""
    padded_p = padded_num_partitions(mesh, num_partitions)
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    kernel = _scalar_kernel(mesh, padded_p, has_l1=l1_cap is not None,
                            need_flags=tuple(need_flags),
                            has_group_clip=has_group_clip)
    args = (key, dpid, dpk, dval, dvalid, linf_cap, l0_cap,
            float(row_clip_lo), float(row_clip_hi), float(middle),
            float(group_clip_lo), float(group_clip_hi))
    if l1_cap is not None:
        args += (l1_cap,)
    return kernel(*args)


@functools.lru_cache(maxsize=None)
def _codec_scalar_kernel(mesh: Mesh, padded_p: int, fmt, has_l1: bool,
                         need_flags, has_group_clip: bool,
                         int_clip=None):
    """Wire-codec decode + bound-and-aggregate, shard-local.

    Each device receives ONE codec bucket row of the [n_dev, W] slab,
    decodes it with elementwise ops (ops/wirecodec.decode_bucket), runs
    the fused kernel, and reduce-scatters the per-partition partials —
    the multi-chip twin of streaming._chunk_step_rle. fmt carries the
    segment-local sort tile geometry (streaming.finish_wire_plan);
    int_clip is the static int32 row-clip pair of the int-accumulation
    gate, or None for the float32 accumulators."""
    from pipelinedp_tpu.ops import streaming

    axes = tuple(mesh.axis_names)
    scatter_axes = _scatter_axes(mesh)

    def local_step(key, row, n_valid, n_uniq, linf_cap, l0_cap, row_clip_lo,
                   row_clip_hi, middle, group_clip_lo, group_clip_hi,
                   *l1_args):
        pid, pk, value, valid, vkw = streaming._decode_for_kernel(
            row[0], n_valid[0], n_uniq[0], fmt)
        accs = columnar.bound_and_aggregate(
            _device_key(key, axes), pid, pk, value, valid,
            num_partitions=padded_p,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi,
            middle=middle,
            group_clip_lo=group_clip_lo,
            group_clip_hi=group_clip_hi,
            l1_cap=l1_args[0] if has_l1 else None,
            need_count=need_flags[0],
            need_sum=need_flags[1],
            need_norm=need_flags[2],
            need_norm_sq=need_flags[3],
            has_group_clip=has_group_clip,
            pid_sorted=fmt.pid_sorted,
            max_segments=fmt.ucap if fmt.pid_sorted else None,
            int_accumulate=int_clip is not None,
            int_clip_lo=int_clip[0] if int_clip is not None else None,
            int_clip_hi=int_clip[1] if int_clip is not None else None,
            **vkw)
        return columnar.PartitionAccumulators(
            *(_reduce_scatter(a, scatter_axes) for a in accs))

    spec = _spec(mesh)
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec) + (P(),) * (8 if has_l1 else 7),
        out_specs=columnar.PartitionAccumulators(*(_part_spec(mesh),) * 5),
        check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _codec_compact_kernel(mesh: Mesh, padded_p: int, fmt, max_groups: int,
                          has_l1: bool, need_flags,
                          has_group_clip: bool, int_clip=None):
    """Compact-merge twin of _codec_scalar_kernel: each device decodes its
    bucket and emits compact per-group subtotal columns
    (columnar.CompactGroups, [max_groups] per device) instead of
    scattering into [padded_p] and reduce-scattering per chunk. The
    per-chunk collectives move to the single merge kernel below."""
    from pipelinedp_tpu.ops import streaming

    axes = tuple(mesh.axis_names)

    def local_step(key, row, n_valid, n_uniq, linf_cap, l0_cap, row_clip_lo,
                   row_clip_hi, middle, group_clip_lo, group_clip_hi,
                   *l1_args):
        pid, pk, value, valid, vkw = streaming._decode_for_kernel(
            row[0], n_valid[0], n_uniq[0], fmt)
        cg = columnar.bound_and_aggregate_compact(
            _device_key(key, axes), pid, pk, value, valid,
            num_partitions=padded_p,
            max_groups=max_groups,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi,
            middle=middle,
            group_clip_lo=group_clip_lo,
            group_clip_hi=group_clip_hi,
            l1_cap=l1_args[0] if has_l1 else None,
            need_count=need_flags[0],
            need_sum=need_flags[1],
            need_norm=need_flags[2],
            need_norm_sq=need_flags[3],
            has_group_clip=has_group_clip,
            pid_sorted=fmt.pid_sorted,
            max_segments=fmt.ucap if fmt.pid_sorted else None,
            int_accumulate=int_clip is not None,
            int_clip_lo=int_clip[0] if int_clip is not None else None,
            int_clip_hi=int_clip[1] if int_clip is not None else None,
            **vkw)
        return columnar.CompactGroups(
            cg.pk, cg.pid_count, cg.count, cg.sum, cg.norm_sum,
            cg.norm_sq_sum, jnp.reshape(cg.n_kept, (1,)))

    spec = _spec(mesh)
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec) + (P(),) * (8 if has_l1 else 7),
        out_specs=columnar.CompactGroups(*(spec,) * 7),
        check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _compact_merge_kernel(mesh: Mesh, padded_p: int, n_c: int, need_flags):
    """Folds n_c chunks of per-device compact group columns into the
    dense sharded accumulators inside ONE executable.

    Bit-parity contract with the legacy chunk loop: the legacy loop runs
    ``accs = accs + reduce_scatter(local_scatter(chunk c))`` chunk by
    chunk, so the merge must keep exactly that per-partition fold order —
    one local [padded_p] scatter (from the compact columns, so the input
    is max_groups entries, not row-scale) and one reduce-scatter per
    chunk, folded in chunk order. The collectives stay per chunk; the
    expensive row/group-scale partition passes are gone."""
    scatter_axes = _scatter_axes(mesh)
    needed = (True,) + tuple(bool(f) for f in need_flags)

    def local_step(accs, *flat):
        cols = list(accs)
        for c in range(n_c):
            chunk = flat[c * 6:(c + 1) * 6]
            cpk = chunk[0]
            for i in range(5):
                if not needed[i]:
                    continue
                partial = jnp.zeros((padded_p,), jnp.float32).at[cpk].add(
                    chunk[1 + i], mode="drop")
                cols[i] = cols[i] + _reduce_scatter(partial, scatter_axes)
        return columnar.PartitionAccumulators(*cols)

    spec = _spec(mesh)
    part = _part_spec(mesh)
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(columnar.PartitionAccumulators(*(part,) * 5),)
        + (spec,) * (6 * n_c),
        out_specs=columnar.PartitionAccumulators(*(part,) * 5),
        check_vma=False)
    return jax.jit(fn)


def stream_bound_and_aggregate(mesh: Mesh,
                               key: jax.Array,
                               pid: np.ndarray,
                               pk: np.ndarray,
                               value,
                               *,
                               num_partitions: int,
                               linf_cap,
                               l0_cap,
                               row_clip_lo,
                               row_clip_hi,
                               middle,
                               group_clip_lo,
                               group_clip_hi,
                               l1_cap=None,
                               n_chunks: Optional[int] = None,
                               value_transfer_dtype=None,
                               need_flags=(True, True, True, True),
                               has_group_clip: bool = True,
                               resilience=None,
                               resume_from=None,
                               compact_merge="auto",
                               segment_sort="auto"
                               ) -> columnar.PartitionAccumulators:
    """Chunked, transfer-overlapped multi-chip bound-and-aggregate.

    Rows are wire-codec-encoded into n_chunks x n_dev pid-disjoint
    buckets (one per device per chunk); each chunk ships as ONE sharded
    [n_dev, W] device_put whose async transfer overlaps the previous
    chunk's kernels — the mesh generalization of the single-device
    streaming pipeline (ops/streaming.py), with identical exactness
    (pid-disjoint buckets bound independently, accumulators add).
    Returns globally-sharded [padded_p] accumulators like
    bound_and_aggregate.

    resilience / resume_from: the runtime resilience bundle and explicit
    checkpoint hook, as on the single-device path (RESILIENCE.md). The
    mesh checkpoints per chunk; OOM degradation does not apply here (the
    chunk granularity is fixed by the mesh shape), so RESOURCE_EXHAUSTED
    re-issues the chunk like a transient fault.

    compact_merge: as on the single-device path — each chunk's devices
    emit compact per-group subtotal columns and ONE merge executable
    folds every chunk (per-chunk reduce-scatters preserved for bit
    parity, but the row/group-scale partition scatters are gone).
    "auto" (default) engages at >= streaming.COMPACT_MIN_PARTITIONS
    padded partitions; False restores the legacy per-chunk
    scatter+reduce-scatter loop.

    segment_sort: the bucketed segment-local sort inside each device's
    chunk kernel, as on the single-device path (streaming
    .stream_bound_and_aggregate) — "auto"/True/False resolve through the
    shared streaming.finish_wire_plan, so mesh and single-device runs of
    the same wire make the same tiling decision. BIT-identical released
    values either way.
    """
    import dataclasses

    from pipelinedp_tpu.ops import streaming, wirecodec

    if resume_from is not None:
        if resilience is None:
            from pipelinedp_tpu import runtime as runtime_lib
            resilience = runtime_lib.StreamResilience()
        resilience = dataclasses.replace(resilience, resume_from=resume_from)
    n = len(pid)
    n_dev = mesh.devices.size
    padded_p = padded_num_partitions(mesh, num_partitions)
    pid = np.asarray(pid)
    if n == 0:
        return bound_and_aggregate(
            mesh, key, pid, pk, np.zeros(0, np.float32),
            np.zeros(0, bool), num_partitions=num_partitions,
            linf_cap=linf_cap, l0_cap=l0_cap, row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi, middle=middle,
            group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
            l1_cap=l1_cap, need_flags=need_flags,
            has_group_clip=has_group_clip)
    n_c = n_chunks or streaming._num_chunks(max(n // n_dev, 1))
    k = n_c * n_dev
    # Shared encode prologue with ops/streaming.py (pid-span validation,
    # width/bit planning, value plan, pid wire mode, native encoder).
    enc, info = wirecodec.make_encoder(
        pid, pk, value, num_partitions=num_partitions, k=k,
        value_transfer_dtype=value_transfer_dtype)
    if enc is not None:
        with enc:
            counts = enc.counts
            cap = wirecodec._round8(int(counts.max()))
            if info.pid_mode == wirecodec.PID_PLANES:
                # Arrival-order pid planes: no host sort at all.
                fmt = wirecodec.WireFormat(
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    cap=cap, ucap=8, value=info.plan,
                    pid_mode=wirecodec.PID_PLANES, bits_pid=info.bits_pid)
                n_uniq = np.zeros(k, dtype=np.int64)

                def emit(c):
                    return enc.emit_range(c * n_dev, (c + 1) * n_dev, fmt)
            elif enc.entry_counts is not None:
                # Entry counts known at prep time: the per-bucket radix
                # sort joins the chunk pipeline (sort chunk c while chunk
                # c-1's sharded device_put + kernels are in flight).
                n_uniq = enc.entry_counts
                fmt = wirecodec.WireFormat(
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    cap=cap,
                    ucap=wirecodec.round_ucap(int(n_uniq.max())),
                    value=info.plan)

                def emit(c):
                    b0, b1 = c * n_dev, (c + 1) * n_dev
                    sorted_uniq = enc.sort_range(b0, b1)
                    if not np.array_equal(sorted_uniq, n_uniq[b0:b1]):
                        # Same corrupted-input guard as the single-device
                        # slab loop (ops/streaming.py): analytic prep
                        # counts must equal the post-sort RLE counts.
                        raise RuntimeError(
                            "wirecodec: prep-time RLE entry counts "
                            "disagree with the sorted buckets")
                    return enc.emit_range(b0, b1, fmt)
            else:
                n_uniq = enc.sort_range(0, k)
                fmt = wirecodec.WireFormat(
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    cap=cap,
                    ucap=wirecodec.round_ucap(int(n_uniq.max())),
                    value=info.plan)

                def emit(c):
                    return enc.emit_range(c * n_dev, (c + 1) * n_dev, fmt)

            # Tile geometry + int-accumulation gate + per-bucket sort cost,
            # resolved exactly as on the single-device path (tile fields
            # are sort geometry, not wire layout, so the emit closures
            # above are unaffected by the replace).
            fmt, int_clip, sort_stats = streaming.finish_wire_plan(
                fmt, segment_sort, info.max_run,
                num_partitions=padded_p, row_clip_lo=row_clip_lo,
                row_clip_hi=row_clip_hi, linf_cap=linf_cap,
                l1_mode=l1_cap is not None,
                group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
                need_flags=tuple(need_flags))
            return _drive_codec_chunks(mesh, key, emit, counts, n_uniq, fmt,
                                     n_c, n_dev, padded_p, linf_cap, l0_cap,
                                     row_clip_lo, row_clip_hi, middle,
                                     group_clip_lo, group_clip_hi, l1_cap,
                                     tuple(need_flags), has_group_clip,
                                     resilience,
                                     lambda: streaming._input_digest(
                                         pid, pk, value),
                                     compact_merge=compact_merge,
                                     int_clip=int_clip,
                                     sort_stats=sort_stats)
    slab, counts, n_uniq, fmt = wirecodec.encode_buckets_numpy(
        pid, pk, value, pid_lo=info.pid_lo, k=k, bytes_pid=info.bytes_pid,
        bits_pk=info.bits_pk, plan=info.plan, pid_mode=info.pid_mode,
        bits_pid=info.bits_pid)
    fmt, int_clip, sort_stats = streaming.finish_wire_plan(
        fmt, segment_sort, info.max_run,
        num_partitions=padded_p, row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi, linf_cap=linf_cap,
        l1_mode=l1_cap is not None,
        group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
        need_flags=tuple(need_flags))
    return _drive_codec_chunks(mesh, key,
                             lambda c: slab[c * n_dev:(c + 1) * n_dev],
                             counts, n_uniq, fmt, n_c,
                             n_dev, padded_p, linf_cap, l0_cap, row_clip_lo,
                             row_clip_hi, middle, group_clip_lo,
                             group_clip_hi, l1_cap, tuple(need_flags),
                             has_group_clip, resilience,
                             lambda: streaming._input_digest(pid, pk, value),
                             compact_merge=compact_merge,
                             int_clip=int_clip, sort_stats=sort_stats)


def replay_resident_wire(mesh: Mesh,
                         key: jax.Array,
                         wire,
                         *,
                         linf_cap,
                         l0_cap,
                         row_clip_lo,
                         row_clip_hi,
                         middle,
                         group_clip_lo,
                         group_clip_hi,
                         l1_cap=None,
                         need_flags=(True, True, True, True),
                         has_group_clip: bool = True,
                         segment_sort="auto",
                         compact_merge="auto",
                         resilience=None) -> columnar.PartitionAccumulators:
    """Answers one query from a mesh-ingested ResidentWire: the retained
    chunks ship sharded (one bucket per device) and fold through the
    same codec chunk kernels as the cold mesh stream — no encode and no
    host sort are re-paid. Bit-identical to
    stream_bound_and_aggregate(mesh, key, <source columns>,
    n_chunks=wire.n_chunks, ...) with the same knobs.
    """
    from pipelinedp_tpu import profiler
    from pipelinedp_tpu.ops import streaming

    n_dev = mesh.devices.size
    if wire.n_dev != n_dev:
        raise ValueError(
            f"handle was ingested for {wire.n_dev} devices; this mesh has "
            f"{n_dev}")
    padded_p = padded_num_partitions(mesh, wire.num_partitions)
    if wire.n_rows == 0:
        part_sharding = NamedSharding(mesh, _part_spec(mesh))
        return columnar.PartitionAccumulators(
            *(jax.device_put(np.zeros(padded_p, np.float32), part_sharding)
              for _ in range(5)))
    profiler.count_event(streaming.EVENT_SERVING_REPLAYS)
    from pipelinedp_tpu.obs import trace as obs_trace
    obs_trace.event("wire_replay", n_chunks=wire.n_chunks, n_dev=n_dev)
    fmt, int_clip, sort_stats = streaming.finish_wire_plan(
        wire.fmt, segment_sort, wire.max_run, num_partitions=padded_p,
        row_clip_lo=row_clip_lo, row_clip_hi=row_clip_hi,
        linf_cap=linf_cap, l1_mode=l1_cap is not None,
        group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
        need_flags=tuple(need_flags))
    return _drive_codec_chunks(
        mesh, key, lambda c: wire.slab[c * n_dev:(c + 1) * n_dev],
        wire.counts, wire.n_uniq, fmt, wire.n_chunks, n_dev, padded_p,
        linf_cap, l0_cap, row_clip_lo, row_clip_hi, middle, group_clip_lo,
        group_clip_hi, l1_cap, tuple(need_flags), has_group_clip,
        resilience, None, compact_merge=compact_merge, int_clip=int_clip,
        sort_stats=sort_stats)


def _reduce_scatter_lanes(x, scatter_axes):
    # Batched twin of _reduce_scatter: lane dim 0 is replicated, the
    # partition dim 1 scatters in the same ICI-first order.
    for axis in scatter_axes:
        x = jax.lax.psum_scatter(x, axis, scatter_dimension=1, tiled=True)
    return x


@functools.lru_cache(maxsize=None)
def _codec_batch_kernel(mesh: Mesh, padded_p: int, fmt, has_l1: bool,
                        need_flags, has_group_clip: bool):
    """Batched twin of _codec_scalar_kernel: ONE launch folds a chunk for
    B query configs. Each device decodes its codec bucket once, vmaps the
    bounding kernel over the per-config (key, caps, clip bounds) lanes,
    and reduce-scatters the [B, padded_p] partials along the partition
    dim. Per-config lanes match that config's sequential mesh replay: the
    per-device key schedule is the same _device_key(fold_in(key_b, c))
    and each lane's bounding math is independent."""
    from pipelinedp_tpu.ops import streaming

    axes = tuple(mesh.axis_names)
    scatter_axes = _scatter_axes(mesh)

    def local_step(keys, row, n_valid, n_uniq, linf_caps, l0_caps,
                   row_clip_los, row_clip_his, middles, group_clip_los,
                   group_clip_his, *l1_args):
        pid, pk, value, valid, vkw = streaming._decode_for_kernel(
            row[0], n_valid[0], n_uniq[0], fmt)

        def one(key, linf_cap, l0_cap, row_clip_lo, row_clip_hi, middle,
                group_clip_lo, group_clip_hi, l1_cap=None):
            return columnar.bound_and_aggregate(
                _device_key(key, axes), pid, pk, value, valid,
                num_partitions=padded_p,
                linf_cap=linf_cap,
                l0_cap=l0_cap,
                row_clip_lo=row_clip_lo,
                row_clip_hi=row_clip_hi,
                middle=middle,
                group_clip_lo=group_clip_lo,
                group_clip_hi=group_clip_hi,
                l1_cap=l1_cap,
                need_count=need_flags[0],
                need_sum=need_flags[1],
                need_norm=need_flags[2],
                need_norm_sq=need_flags[3],
                has_group_clip=has_group_clip,
                pid_sorted=fmt.pid_sorted,
                max_segments=fmt.ucap if fmt.pid_sorted else None,
                **vkw)

        if has_l1:
            accs = jax.vmap(one)(keys, linf_caps, l0_caps, row_clip_los,
                                 row_clip_his, middles, group_clip_los,
                                 group_clip_his, l1_args[0])
        else:
            accs = jax.vmap(one)(keys, linf_caps, l0_caps, row_clip_los,
                                 row_clip_his, middles, group_clip_los,
                                 group_clip_his)
        return columnar.PartitionAccumulators(
            *(_reduce_scatter_lanes(a, scatter_axes) for a in accs))

    spec = _spec(mesh)
    lane_part = P(None, _scatter_axes(mesh))
    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec) + (P(),) * (8 if has_l1 else 7),
        out_specs=columnar.PartitionAccumulators(*(lane_part,) * 5),
        check_vma=False)
    return jax.jit(fn)


@jax.jit
def _fold_lane_keys(keys, c):
    # The engine's per-chunk key schedule, one lane per config.
    return jax.vmap(jax.random.fold_in, in_axes=(0, None))(keys, c)


def replay_resident_wire_batched(mesh: Mesh,
                                 keys,
                                 wire,
                                 *,
                                 linf_caps,
                                 l0_caps,
                                 row_clip_los,
                                 row_clip_his,
                                 middles,
                                 group_clip_los,
                                 group_clip_his,
                                 l1_caps=None,
                                 need_flags=(True, True, True, True),
                                 has_group_clip: bool = True
                                 ) -> columnar.PartitionAccumulators:
    """Folds a mesh-ingested ResidentWire for B query configs in ONE
    launch per chunk — the multi-chip twin of
    streaming.replay_resident_wire_batched. Returns [B, padded_p]
    PartitionAccumulators sharded over the partition dim (lane dim
    replicated); lane b is bit-identical to that config's sequential
    replay_resident_wire(mesh, ...) fold, and therefore to its cold mesh
    run. Uses the parity-oracle statics (untiled packed sort, float32
    payload/accumulation, no hash bins), which the segment-sort parity
    matrix pins bit-identical to every other mode.
    """
    from pipelinedp_tpu import profiler
    from pipelinedp_tpu.ops import streaming

    import dataclasses

    n_dev = mesh.devices.size
    if wire.n_dev != n_dev:
        raise ValueError(
            f"handle was ingested for {wire.n_dev} devices; this mesh has "
            f"{n_dev}")
    padded_p = padded_num_partitions(mesh, wire.num_partitions)
    B = len(linf_caps)
    lane_sharding = NamedSharding(mesh, P(None, _scatter_axes(mesh)))
    if wire.n_rows == 0:
        return columnar.PartitionAccumulators(
            *(jax.device_put(np.zeros((B, padded_p), np.float32),
                             lane_sharding) for _ in range(5)))
    profiler.count_event(streaming.EVENT_SERVING_REPLAYS)
    from pipelinedp_tpu.obs import trace as obs_trace
    obs_trace.event("wire_replay_batched", n_chunks=wire.n_chunks,
                    n_dev=n_dev, width=B)
    fmt = dataclasses.replace(wire.fmt, tile_rows=0, tile_slack=0,
                              hash_bins=0, hash_bin_rows=0,
                              sort_value_narrow=False)
    kernel = _codec_batch_kernel(mesh, padded_p, fmt,
                                 l1_caps is not None, tuple(need_flags),
                                 has_group_clip)
    keys = jnp.stack([jnp.asarray(k) for k in keys])
    linf = jnp.asarray(np.asarray(linf_caps, dtype=np.int32))
    l0 = jnp.asarray(np.asarray(l0_caps, dtype=np.int32))
    rlo = jnp.asarray(np.asarray(row_clip_los, dtype=np.float32))
    rhi = jnp.asarray(np.asarray(row_clip_his, dtype=np.float32))
    mid = jnp.asarray(np.asarray(middles, dtype=np.float32))
    glo = jnp.asarray(np.asarray(group_clip_los, dtype=np.float32))
    ghi = jnp.asarray(np.asarray(group_clip_his, dtype=np.float32))
    l1 = (None if l1_caps is None
          else jnp.asarray(np.asarray(l1_caps, dtype=np.int32)))
    sharding = NamedSharding(mesh, _spec(mesh))
    counts = np.asarray(wire.counts, dtype=np.int32)
    n_uniq = np.asarray(wire.n_uniq, dtype=np.int32)
    cost = columnar.sort_cost(
        fmt.cap, num_partitions=padded_p,
        max_segments=fmt.ucap if fmt.pid_sorted else None,
        pid_sorted=fmt.pid_sorted, l1_mode=l1 is not None)
    accs = None
    for c in range(wire.n_chunks):
        dslab = jax.device_put(wire.slab[c * n_dev:(c + 1) * n_dev],
                               sharding)
        dvalid = jax.device_put(counts[c * n_dev:(c + 1) * n_dev],
                                sharding)
        duniq = jax.device_put(n_uniq[c * n_dev:(c + 1) * n_dev], sharding)
        args = (_fold_lane_keys(keys, c), dslab, dvalid, duniq,
                linf, l0, rlo, rhi, mid, glo, ghi)
        if l1 is not None:
            args += (l1,)
        chunk_accs = kernel(*args)
        # First chunk's partials ARE the accumulators, exactly as
        # _MeshPlacement.step folds the sequential replay.
        accs = (chunk_accs if accs is None else
                columnar.PartitionAccumulators(
                    *(a + b for a, b in zip(accs, chunk_accs))))
        # ONE launch covers all B configs across n_dev bucket stages.
        profiler.count_event(streaming.EVENT_SERVING_LAUNCHES)
        profiler.count_event(columnar.EVENT_SORT_ROWS,
                             int(cost["rows"]) * B * n_dev)
        profiler.count_event(columnar.EVENT_SORT_BYTES,
                             int(cost["operand_bytes"]) * B * n_dev)
    return accs


class _MeshPlacement(driver_lib.DevicePlacement):
    """Mesh strategy for the unified slab driver (runtime/driver.py owns
    the loop; this class owns how a chunk's sharded slab lands on the
    mesh and how chunk partials fold).

    One chunk per slab window: the chunk granularity is fixed by the
    mesh shape (n_dev codec buckets per chunk), so device OOM has no
    slab budget to degrade — it re-issues like a transient fault.
    Chunk accumulators are summed, never donated, so retrying a chunk
    can never read poisoned state.
    """

    stage_prefix = "dp/mesh_stream_chunk_"
    prefetch_prefix = "pdp-chunk-prefetch"
    degradable = False
    donates = False

    def __init__(self, *, transfer_fn, run_chunk, part_sharding, merge_fn,
                 compact, snapshot_fn):
        self._transfer_fn = transfer_fn
        self._run_chunk = run_chunk
        self._part_sharding = part_sharding
        self._merge_fn = merge_fn
        self._snapshot_fn = snapshot_fn
        self.compact = compact

    def init_state(self):
        # None until the first chunk: the first chunk's partials ARE the
        # accumulators (no zeros + add), exactly as the legacy loop.
        return None, None

    def transfer(self, slab, s0, s1):
        return self._transfer_fn(slab, s0)

    def step(self, c, payload, offset, accs, qhist):
        chunk_accs = self._run_chunk(c, payload)
        if accs is None:
            return chunk_accs, None
        return columnar.PartitionAccumulators(
            *(a + b for a, b in zip(accs, chunk_accs))), None

    def compact_step(self, c, payload, offset):
        return self._run_chunk(c, payload)

    def merge_pending(self, accs, pending):
        return self._merge_fn(accs, pending)

    def snapshot(self, accs, qhist):
        return self._snapshot_fn(accs, qhist)

    def restore(self, cp, expects_qhist):
        accs = columnar.PartitionAccumulators(
            *(jax.device_put(np.array(a), self._part_sharding)
              for a in cp.accs))
        return accs, None

def _drive_codec_chunks(mesh, key, emit, counts, n_uniq, fmt, n_c, n_dev,
                        padded_p, linf_cap, l0_cap, row_clip_lo,
                        row_clip_hi, middle, group_clip_lo, group_clip_hi,
                        l1_cap, need_flags, has_group_clip, resilience=None,
                        data_digest_fn=None, compact_merge: bool = True,
                        int_clip=None, sort_stats=None):
    """Runs the mesh chunk schedule on the unified slab driver
    (runtime.SlabDriver — the same loop body as the single-device path,
    so checkpoint/resume, retry, prefetch, compact merge, fault
    injection and the dispatch watchdog are shared, not twinned).

    Each chunk is one slab window: ``emit(c)`` is the pure host encode
    (prefetchable, discardable), the transfer ships the chunk's sharded
    [n_dev, W] slab plus its count/entry-count rows, and the chunk
    kernel folds the reduce-scattered partials into the running sharded
    accumulators. In compact-merge mode per-device compact group columns
    collect per chunk and fold into the dense sharded accumulators only
    at checkpoints and once at the end (_compact_merge_kernel, which
    keeps the legacy per-partition fold order for bit parity)."""
    from pipelinedp_tpu import profiler
    from pipelinedp_tpu.ops import streaming

    import dataclasses

    max_groups = None
    if (streaming._compact_enabled(compact_merge, padded_p)
            and fmt.pid_sorted):
        max_groups = columnar.compact_group_bound(fmt.cap, fmt.ucap,
                                                  l0_cap)
    compact = max_groups is not None
    # Plain-int pair so the lru_cached kernel builders key on it.
    int_clip_key = (None if int_clip is None
                    else (int(int_clip[0]), int(int_clip[1])))

    def build_kernel(f):
        if compact:
            return _codec_compact_kernel(mesh, padded_p, f, max_groups,
                                         l1_cap is not None, need_flags,
                                         has_group_clip, int_clip_key)
        return _codec_scalar_kernel(mesh, padded_p, f,
                                    l1_cap is not None, need_flags,
                                    has_group_clip, int_clip_key)

    kernel = build_kernel(fmt)
    # Per-chunk demotion target of the hash-binned group stage: a chunk
    # whose RLE entry count exceeds the static bin count runs the tiled
    # kernel (built lazily on first demotion; decided on host counts
    # that ride the wire fingerprint, so replays/resumes demote
    # identically).
    hash_on = fmt.hash_bins > 0 and fmt.pid_sorted
    fmt_demoted = (dataclasses.replace(fmt, hash_bins=0, hash_bin_rows=0)
                   if hash_on else fmt)
    scatter_passes = 1 + sum(bool(f) for f in need_flags)
    sharding = NamedSharding(mesh, _spec(mesh))
    part_sharding = NamedSharding(mesh, _part_spec(mesh))
    counts = np.asarray(counts, dtype=np.int32)
    n_uniq = np.asarray(n_uniq, dtype=np.int32)

    def credit(st, rows):
        # Every device sorts (or hash-bins) its own bucket, so one chunk
        # executes n_dev bucket stages; the hash pass/occupancy counters
        # count per LAUNCH (one chunk = one kernel), like the demotion
        # counter.
        if st is None:
            return
        streaming._count_sort_stats(
            {name: st[name] * n_dev
             for name in ("rows", "tiles", "operand_bytes")})
        if st.get("kind") == "hash":
            profiler.count_event(columnar.EVENT_HASH_PASSES)
            cells = max(int(st.get("grid_cells", 0)) * n_dev, 1)
            profiler.count_event(columnar.EVENT_HASH_OCCUPANCY,
                                 min(100, (100 * rows) // cells))

    def transfer_chunk(slab, c):
        dslab = jax.device_put(slab, sharding)
        dvalid = jax.device_put(counts[c * n_dev:(c + 1) * n_dev],
                                sharding)
        duniq = jax.device_put(n_uniq[c * n_dev:(c + 1) * n_dev],
                               sharding)
        return dslab, dvalid, duniq

    def run_chunk(c, payload):
        dslab, dvalid, duniq = payload
        use_kernel, st = kernel, sort_stats
        if (hash_on and int(n_uniq[c * n_dev:(c + 1) * n_dev].max())
                > fmt.hash_bins):
            profiler.count_event(columnar.EVENT_HASH_DEMOTIONS)
            use_kernel = build_kernel(fmt_demoted)
            st = (sort_stats or {}).get("demoted")
        credit(st, int(counts[c * n_dev:(c + 1) * n_dev].sum()))
        args = (jax.random.fold_in(key, c), dslab, dvalid, duniq,
                linf_cap, l0_cap, float(row_clip_lo), float(row_clip_hi),
                float(middle), float(group_clip_lo), float(group_clip_hi))
        if l1_cap is not None:
            args += (l1_cap,)
        return use_kernel(*args)

    def merge_pending(accs, pending):
        if accs is None:
            accs = columnar.PartitionAccumulators(
                *(jax.device_put(np.zeros(padded_p, np.float32),
                                 part_sharding) for _ in range(5)))
        max_kept = int(jax.device_get(jnp.max(
            jnp.concatenate([p.n_kept for p in pending]))))
        if max_kept > max_groups:
            raise RuntimeError(
                f"compact merge: a chunk kept {max_kept} groups, above "
                f"the static bound {max_groups} — the pid-sorted wire "
                f"contract was violated; refusing to release truncated "
                f"accumulators")
        profiler.count_event(streaming.EVENT_COMPACT_MERGE_SCATTERS,
                             scatter_passes * len(pending))
        merge = _compact_merge_kernel(mesh, padded_p, len(pending),
                                      tuple(need_flags))
        flat = [a for p in pending for a in p[:6]]
        return merge(accs, *flat)

    placement = _MeshPlacement(
        transfer_fn=transfer_chunk, run_chunk=run_chunk,
        part_sharding=part_sharding, merge_fn=merge_pending,
        compact=compact, snapshot_fn=streaming._snapshot_host)
    plan = driver_lib.SlabPlan(
        n_chunks=n_c,
        window_chunks=1,  # chunk granularity is fixed by the mesh shape
        fmt_desc=repr(("mesh", n_dev, fmt)),
        counts=counts,
        n_uniq=n_uniq,
        scatter_passes=scatter_passes,
        quantile=False,
        data_digest_fn=data_digest_fn,
        prefetch_depth=streaming.prefetch_depth())
    accs, _ = driver_lib.SlabDriver(
        placement, plan, lambda s0, s1: emit(s0), key, resilience).run()
    return accs


def bound_and_aggregate_vector(mesh: Mesh,
                               key: jax.Array,
                               pid: np.ndarray,
                               pk: np.ndarray,
                               value: np.ndarray,
                               valid: np.ndarray,
                               *,
                               num_partitions: int,
                               linf_cap,
                               l0_cap,
                               max_norm,
                               norm_ord: int,
                               l1_cap=None,
                               pid_sorted: bool = False,
                               max_segments=None):
    """Multi-chip VECTOR_SUM path; see bound_and_aggregate.

    pid_sorted: the caller staged rows pre-sorted by pid (host argsort
    before stage_rows — the stable shard partition keeps every shard's
    block pid-sorted), so each device runs the packed 3-key bounding
    sort instead of the general 4-key one; max_segments bounds any one
    shard's distinct pids."""
    padded_p = padded_num_partitions(mesh, num_partitions)
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    kernel = _vector_kernel(mesh, padded_p, norm_ord,
                            has_l1=l1_cap is not None,
                            pid_sorted=pid_sorted,
                            max_segments=max_segments)
    args = (key, dpid, dpk, dval, dvalid, linf_cap, l0_cap, float(max_norm))
    if l1_cap is not None:
        args += (l1_cap,)
    return kernel(*args)


def build_finalize_epilogue(mesh: Mesh, plan):
    """Mesh variant of the fused finalization epilogue (ops/finalize.py).

    The accumulators arrive sharded over the partition dimension (the
    reduce-scatter layout, _part_spec); the whole epilogue — selection,
    batched noise, metric math, thresholding — compiles as one executable
    under XLA's SPMD partitioner, with explicit sharding constraints
    pinning every released column to the partition layout so no
    all-gather sneaks onto the serving path before the single batched
    device→host transfer.

    Deliberately NOT a per-device-key shard_map: the PRNG draws must stay
    *globally* keyed so mesh and single-device runs of the same seed
    release identical noise (the bit-parity contract pinned by
    tests/finalize_test.py). Elementwise ops over [padded_p] arrays
    partition perfectly under SPMD anyway — shard_map would buy nothing
    but a different (per-shard) noise stream.
    """
    from pipelinedp_tpu.ops import finalize as finalize_ops

    part = NamedSharding(mesh, _part_spec(mesh))

    def body(op):
        columns, keep = finalize_ops.epilogue_body(plan, op)
        columns = {
            name: jax.lax.with_sharding_constraint(col, part)
            for name, col in columns.items()
        }
        return columns, jax.lax.with_sharding_constraint(keep, part)

    return jax.jit(body)
