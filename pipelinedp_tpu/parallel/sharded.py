"""Multi-chip execution: shard_map over a 2D ('dp', 'mp') device mesh.

This is the TPU-native replacement for the reference's distributed shuffle
(Beam runner / Spark shuffle behind group_by_key and
combine_accumulators_per_key, pipeline_backend.py:223-474; SURVEY.md §2.5):

  * rows are sharded over all mesh devices (data parallelism across both
    axes) — the host loader hash-shards rows by privacy id, so each privacy
    unit's rows are local to one device and contribution bounding is exact
    without any cross-device exchange;
  * each device runs the fused bound-and-aggregate kernel on its shard,
    producing per-partition partial accumulators [num_partitions];
  * partials are combined with `psum_scatter` over 'mp' then 'dp' — the
    reduce-scatter rides ICI and leaves every device holding the *full* sum
    for a distinct 1/(dp*mp) slice of the partition space (this is the
    shuffle);
  * partition selection and noise generation then run fully sharded — every
    chip noises only its partition slice — and results are all-gathered.

The same step compiles for any mesh shape; __graft_entry__.dryrun_multichip
exercises it on a virtual CPU mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from pipelinedp_tpu.ops import columnar, noise as noise_ops
from pipelinedp_tpu.ops import selection as selection_ops


def make_mesh(n_devices: Optional[int] = None,
              dp: Optional[int] = None,
              mp: Optional[int] = None,
              devices=None) -> Mesh:
    """Builds a ('dp', 'mp') mesh over the available devices.

    Default factorization puts the larger factor on 'dp' (rows usually
    outnumber partitions per device).
    """
    if devices is None:
        devices = jax.devices()
    n = n_devices or len(devices)
    if dp is None or mp is None:
        mp = 1
        for candidate in range(int(np.sqrt(n)), 0, -1):
            if n % candidate == 0:
                mp = candidate
                break
        dp = n // mp
    if dp * mp != n:
        raise ValueError(f"dp*mp={dp*mp} != n_devices={n}")
    return Mesh(np.asarray(devices[:n]).reshape(dp, mp), ("dp", "mp"))


def shard_rows_by_pid(pid: np.ndarray, pk: np.ndarray, value: np.ndarray,
                      n_shards: int
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
    """Host-side loader step: hash-shard rows by privacy id and pad shards
    to equal length.

    Returns arrays of shape [n_shards * shard_len] laid out shard-major,
    plus the validity mask for padding rows. Keeping each pid on one shard
    makes L0/Linf bounding exact with zero cross-device row exchange.
    """
    shard_of_row = pid % n_shards
    order = np.argsort(shard_of_row, kind="stable")
    pid, pk, value = pid[order], pk[order], value[order]
    shard_of_row = shard_of_row[order]
    counts = np.bincount(shard_of_row, minlength=n_shards)
    shard_len = int(counts.max()) if len(pid) else 1
    total = n_shards * shard_len
    out_pid = np.zeros(total, dtype=pid.dtype)
    out_pk = np.zeros(total, dtype=pk.dtype)
    out_val = np.zeros((total,) + value.shape[1:], dtype=value.dtype)
    out_valid = np.zeros(total, dtype=bool)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    for s in range(n_shards):
        lo, n_rows = offsets[s], counts[s]
        dst = s * shard_len
        out_pid[dst:dst + n_rows] = pid[lo:lo + n_rows]
        out_pk[dst:dst + n_rows] = pk[lo:lo + n_rows]
        out_val[dst:dst + n_rows] = value[lo:lo + n_rows]
        out_valid[dst:dst + n_rows] = True
    return out_pid, out_pk, out_val, out_valid


class ShardedDPResult(NamedTuple):
    """Per-partition outputs, global [num_partitions_padded] arrays."""
    count: jnp.ndarray
    sum: jnp.ndarray
    pid_count: jnp.ndarray
    keep_mask: jnp.ndarray


def build_sharded_aggregate_step(mesh: Mesh, num_partitions: int):
    """Compiles the full sharded DP aggregation step for a mesh.

    num_partitions is padded to a multiple of the device count so the
    partition dimension shards evenly.
    """
    n_dev = mesh.devices.size
    padded_p = ((num_partitions + n_dev - 1) // n_dev) * n_dev

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, clip_lo,
                   clip_hi, noise_scale, noise_granularity, is_gaussian,
                   sel_scalars):
        # Per-device PRNG stream.
        dp_idx = jax.lax.axis_index("dp")
        mp_idx = jax.lax.axis_index("mp")
        dev_key = jax.random.fold_in(jax.random.fold_in(key, dp_idx), mp_idx)
        k_kernel, k_sel, k_noise1, k_noise2 = jax.random.split(dev_key, 4)

        accs = columnar.bound_and_aggregate(
            k_kernel, pid, pk, value, valid,
            num_partitions=padded_p,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            row_clip_lo=clip_lo,
            row_clip_hi=clip_hi,
            middle=0.0,
            group_clip_lo=-jnp.inf,
            group_clip_hi=jnp.inf)

        # The distributed shuffle: reduce partials over all devices while
        # scattering the partition dimension (ICI reduce-scatter).
        def reduce_scatter(x):
            # 'dp' first, then 'mp', so the slice held by device (d, m) is
            # chunk d*mp + m — matching the P(('dp','mp')) output layout.
            x = jax.lax.psum_scatter(x, "dp", scatter_dimension=0, tiled=True)
            return jax.lax.psum_scatter(x, "mp", scatter_dimension=0,
                                        tiled=True)

        count = reduce_scatter(accs.count)
        total = reduce_scatter(accs.sum)
        pid_count = reduce_scatter(accs.pid_count)

        # Selection + noise, sharded over the partition slice.
        sel_params = selection_ops.SelectionParams(
            kind=selection_ops.TRUNCATED_GEOMETRIC,
            eps_p=sel_scalars[0], delta_p=sel_scalars[1], n1=sel_scalars[2],
            pi_n1=sel_scalars[3], pi_inf=sel_scalars[4])
        keep, _ = selection_ops.select_partitions(k_sel, pid_count,
                                                  sel_params, pid_count > 0)
        dp_count = noise_ops.add_noise(k_noise1, count, is_gaussian,
                                       noise_scale, noise_granularity)
        dp_sum = noise_ops.add_noise(k_noise2, total, is_gaussian,
                                     noise_scale, noise_granularity)
        return ShardedDPResult(dp_count, dp_sum, pid_count, keep)

    row_spec = P(("dp", "mp"))
    part_spec = P(("dp", "mp"))
    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), row_spec, row_spec, row_spec, row_spec, P(), P(), P(),
                  P(), P(), P(), P(), P()),
        out_specs=ShardedDPResult(part_spec, part_spec, part_spec, part_spec),
        check_vma=False)

    @jax.jit
    def step(key, pid, pk, value, valid, linf_cap, l0_cap, clip_lo, clip_hi,
             noise_scale, noise_granularity, is_gaussian, sel_scalars):
        return sharded(key, pid, pk, value, valid, linf_cap, l0_cap, clip_lo,
                       clip_hi, noise_scale, noise_granularity, is_gaussian,
                       sel_scalars)

    return step, padded_p
