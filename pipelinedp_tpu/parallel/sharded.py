"""Multi-chip execution: shard_map over a 2D ('dp', 'mp') device mesh.

This is the TPU-native replacement for the reference's distributed shuffle
(Beam runner / Spark shuffle behind group_by_key and
combine_accumulators_per_key, pipeline_backend.py:223-474; SURVEY.md §2.5):

  * rows are sharded over all mesh devices (data parallelism across both
    axes) — the host loader hash-shards rows by privacy id, so each privacy
    unit's rows are local to one device and contribution bounding is exact
    without any cross-device exchange;
  * each device runs the fused bound-and-aggregate kernel on its shard,
    producing per-partition partial accumulators [padded_p];
  * partials are combined with `psum_scatter` over 'dp' then 'mp' — the
    reduce-scatter rides ICI and leaves every device holding the *full* sum
    for a distinct 1/(dp*mp) slice of the partition space (this is the
    shuffle);
  * the returned accumulators are global jax.Arrays sharded over the
    partition dimension, so everything downstream — partition selection,
    per-mechanism noise, metric math — runs sharded too under XLA's SPMD
    partitioner without further shard_map plumbing.

JaxDPEngine(mesh=...) routes its fused kernel through here; every metric,
selection strategy, and noise mechanism the engine supports works on any
mesh shape unchanged. __graft_entry__.dryrun_multichip exercises the full
engine path on a virtual CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pipelinedp_tpu.ops import columnar
from pipelinedp_tpu.ops import quantiles as quantile_ops

ROW_SPEC = P(("dp", "mp"))
PART_SPEC = P(("dp", "mp"))


def make_mesh(n_devices: Optional[int] = None,
              dp: Optional[int] = None,
              mp: Optional[int] = None,
              devices=None) -> Mesh:
    """Builds a ('dp', 'mp') mesh over the available devices.

    Default factorization puts the larger factor on 'dp' (rows usually
    outnumber partitions per device).
    """
    if devices is None:
        devices = jax.devices()
    n = n_devices or len(devices)
    if dp is None or mp is None:
        mp = 1
        for candidate in range(int(np.sqrt(n)), 0, -1):
            if n % candidate == 0:
                mp = candidate
                break
        dp = n // mp
    if dp * mp != n:
        raise ValueError(f"dp*mp={dp*mp} != n_devices={n}")
    return Mesh(np.asarray(devices[:n]).reshape(dp, mp), ("dp", "mp"))


def padded_num_partitions(mesh: Mesh, num_partitions: int) -> int:
    """num_partitions rounded up so the partition dim shards evenly."""
    n_dev = mesh.devices.size
    return ((num_partitions + n_dev - 1) // n_dev) * n_dev


def shard_rows_by_pid(pid: np.ndarray,
                      pk: np.ndarray,
                      value: np.ndarray,
                      n_shards: int,
                      valid: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
    """Host-side loader step: hash-shard rows by privacy id and pad shards
    to equal length.

    Returns arrays of shape [n_shards * shard_len] laid out shard-major,
    plus the validity mask for padding rows. Keeping each pid on one shard
    makes L0/Linf bounding exact with zero cross-device row exchange.
    """
    # Multiplicative hash, not bare modulo: raw (unfactorized) id spaces
    # are often structured (all-even ids, per-site ranges) and would skew
    # a low-bits split, doubling shard padding.
    hashed = ((pid.astype(np.uint32) * np.uint32(2654435761)) >>
              np.uint32(16))
    shard_of_row = hashed % np.uint32(n_shards)
    order = np.argsort(shard_of_row, kind="stable")
    pid, pk, value = pid[order], pk[order], value[order]
    valid = (np.ones(len(pid), dtype=bool)
             if valid is None else np.asarray(valid)[order])
    shard_of_row = shard_of_row[order]
    counts = np.bincount(shard_of_row, minlength=n_shards)
    shard_len = int(counts.max()) if len(pid) else 1
    total = n_shards * shard_len
    out_pid = np.zeros(total, dtype=pid.dtype)
    out_pk = np.zeros(total, dtype=pk.dtype)
    out_val = np.zeros((total,) + value.shape[1:], dtype=value.dtype)
    out_valid = np.zeros(total, dtype=bool)
    offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
    for s in range(n_shards):
        lo, n_rows = offsets[s], counts[s]
        dst = s * shard_len
        out_pid[dst:dst + n_rows] = pid[lo:lo + n_rows]
        out_pk[dst:dst + n_rows] = pk[lo:lo + n_rows]
        out_val[dst:dst + n_rows] = value[lo:lo + n_rows]
        out_valid[dst:dst + n_rows] = valid[lo:lo + n_rows]
    return out_pid, out_pk, out_val, out_valid


def _device_key(key):
    """Independent PRNG stream per mesh position."""
    dp_idx = jax.lax.axis_index("dp")
    mp_idx = jax.lax.axis_index("mp")
    return jax.random.fold_in(jax.random.fold_in(key, dp_idx), mp_idx)


def _reduce_scatter(x):
    # 'dp' first, then 'mp', so the slice held by device (d, m) is chunk
    # d*mp + m — matching the P(('dp','mp')) output layout.
    x = jax.lax.psum_scatter(x, "dp", scatter_dimension=0, tiled=True)
    return jax.lax.psum_scatter(x, "mp", scatter_dimension=0, tiled=True)


@functools.lru_cache(maxsize=None)
def _scalar_kernel(mesh: Mesh, padded_p: int, has_l1: bool = False):
    """Sharded twin of columnar.bound_and_aggregate for a given mesh.

    has_l1 compiles the max_contributions variant (an extra runtime l1_cap
    scalar and the per-pid total sample in the local kernel) — shards are
    pid-disjoint, so per-shard L1 sampling is exact.
    """

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, row_clip_lo,
                   row_clip_hi, middle, group_clip_lo, group_clip_hi,
                   *l1_args):
        accs = columnar.bound_and_aggregate(
            _device_key(key), pid, pk, value, valid,
            num_partitions=padded_p,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi,
            middle=middle,
            group_clip_lo=group_clip_lo,
            group_clip_hi=group_clip_hi,
            l1_cap=l1_args[0] if has_l1 else None)
        return jax.tree.map(_reduce_scatter, accs)

    fn = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + (ROW_SPEC,) * 4 + (P(),) * (8 if has_l1 else 7),
        out_specs=columnar.PartitionAccumulators(*([PART_SPEC] * 5)),
        check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _vector_kernel(mesh: Mesh, padded_p: int, norm_ord: int,
                   has_l1: bool = False):
    """Sharded twin of columnar.bound_and_aggregate_vector."""

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, max_norm,
                   *l1_args):
        vector_sums, accs = columnar.bound_and_aggregate_vector(
            _device_key(key), pid, pk, value, valid,
            num_partitions=padded_p,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            max_norm=max_norm,
            norm_ord=norm_ord,
            l1_cap=l1_args[0] if has_l1 else None)
        return (_reduce_scatter(vector_sums),
                jax.tree.map(_reduce_scatter, accs))

    fn = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + (ROW_SPEC,) * 4 + (P(),) * (4 if has_l1 else 3),
        out_specs=(PART_SPEC,
                   columnar.PartitionAccumulators(*([PART_SPEC] * 5))),
        check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _quantile_kernel(mesh: Mesh, padded_p: int, num_leaves: int,
                     has_l1: bool = False):
    """Sharded leaf-histogram kernel for the batched quantile trees."""

    def local_step(key, pid, pk, value, valid, linf_cap, l0_cap, lower,
                   upper, *l1_args):
        mask = columnar.bound_row_mask(_device_key(key), pid, pk, valid,
                                       linf_cap, l0_cap,
                                       l1_cap=l1_args[0] if has_l1 else None)
        hist = quantile_ops.leaf_histograms(pk, value, mask,
                                            num_partitions=padded_p,
                                            num_leaves=num_leaves,
                                            lower=lower,
                                            upper=upper)
        return _reduce_scatter(hist)

    fn = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(),) + (ROW_SPEC,) * 4 + (P(),) * (5 if has_l1 else 4),
        out_specs=PART_SPEC,
        check_vma=False)
    return jax.jit(fn)


def quantile_leaf_histograms(mesh: Mesh, key, pid, pk, value, valid, *,
                             num_partitions: int, num_leaves: int, lower,
                             upper, linf_cap, l0_cap, l1_cap=None):
    """Multi-chip [padded_p, num_leaves] quantile-tree leaf counts."""
    padded_p = padded_num_partitions(mesh, num_partitions)
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    kernel = _quantile_kernel(mesh, padded_p, num_leaves,
                              has_l1=l1_cap is not None)
    args = (key, dpid, dpk, dval, dvalid, linf_cap, l0_cap, float(lower),
            float(upper))
    if l1_cap is not None:
        args += (l1_cap,)
    return kernel(*args)


def _shard_and_put(mesh: Mesh, pid, pk, value, valid):
    """Stages host rows onto the mesh; passes through already-staged
    jax.Arrays so callers running several kernels over the same rows (e.g.
    aggregate + quantile histogram) pay the host shuffle and transfer once.
    """
    if isinstance(pid, jax.Array):
        return pid, pk, value, valid
    n_dev = mesh.devices.size
    spid, spk, sval, svalid = shard_rows_by_pid(np.asarray(pid),
                                                np.asarray(pk),
                                                np.asarray(value), n_dev,
                                                np.asarray(valid))
    sharding = NamedSharding(mesh, ROW_SPEC)
    return tuple(
        jax.device_put(a, sharding) for a in (spid, spk, sval, svalid))


def stage_rows(mesh: Mesh, pid, pk, value, valid):
    """Public staging step: hash-shard + device_put once, reuse across
    kernels."""
    return _shard_and_put(mesh, pid, pk, value, valid)


def bound_and_aggregate(mesh: Mesh,
                        key: jax.Array,
                        pid: np.ndarray,
                        pk: np.ndarray,
                        value: np.ndarray,
                        valid: np.ndarray,
                        *,
                        num_partitions: int,
                        linf_cap,
                        l0_cap,
                        row_clip_lo,
                        row_clip_hi,
                        middle,
                        group_clip_lo,
                        group_clip_hi,
                        l1_cap=None) -> columnar.PartitionAccumulators:
    """Multi-chip bound-and-aggregate: host rows in, global sharded
    [padded_p] accumulators out (padding partitions are all-zero; callers
    trim to num_partitions when materializing)."""
    padded_p = padded_num_partitions(mesh, num_partitions)
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    kernel = _scalar_kernel(mesh, padded_p, has_l1=l1_cap is not None)
    args = (key, dpid, dpk, dval, dvalid, linf_cap, l0_cap,
            float(row_clip_lo), float(row_clip_hi), float(middle),
            float(group_clip_lo), float(group_clip_hi))
    if l1_cap is not None:
        args += (l1_cap,)
    return kernel(*args)


def bound_and_aggregate_vector(mesh: Mesh,
                               key: jax.Array,
                               pid: np.ndarray,
                               pk: np.ndarray,
                               value: np.ndarray,
                               valid: np.ndarray,
                               *,
                               num_partitions: int,
                               linf_cap,
                               l0_cap,
                               max_norm,
                               norm_ord: int,
                               l1_cap=None):
    """Multi-chip VECTOR_SUM path; see bound_and_aggregate."""
    padded_p = padded_num_partitions(mesh, num_partitions)
    dpid, dpk, dval, dvalid = _shard_and_put(mesh, pid, pk, value, valid)
    kernel = _vector_kernel(mesh, padded_p, norm_ord,
                            has_l1=l1_cap is not None)
    args = (key, dpid, dpk, dval, dvalid, linf_cap, l0_cap, float(max_norm))
    if l1_cap is not None:
        args += (l1_cap,)
    return kernel(*args)
