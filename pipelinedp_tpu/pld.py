"""Privacy Loss Distribution (PLD) accounting, implemented on numpy.

The reference delegates PLD accounting to Google's ``dp_accounting`` package
(budget_accounting.py:27-32, 579-619). That package is not vendored here, so
this module provides a self-contained implementation of the surface the
framework needs:

  * ``from_laplace_mechanism(parameter, value_discretization_interval)``
  * ``from_gaussian_mechanism(standard_deviation, value_discretization_interval)``
  * ``from_privacy_parameters(eps, delta, value_discretization_interval)``
  * ``PrivacyLossDistribution.compose`` / ``self_compose``
  * ``PrivacyLossDistribution.get_delta_for_epsilon``
  * ``PrivacyLossDistribution.get_epsilon_for_delta``

Representation: a PLD is the distribution of the privacy loss random variable
L(x) = ln(P(x)/Q(x)) for x ~ P, where P is the mechanism output on a dataset D
and Q on an adjacent D'. We store a pessimistic discretization: probability
mass on the grid ``loss = (offset + i) * interval``, each continuous loss
rounded UP to the next grid point (which can only over-estimate delta, never
under-estimate — the same convention as the reference library), plus an
``infinity_mass`` for events impossible under Q.

The hockey-stick divergence gives
    delta(eps) = infinity_mass + sum_{l_i > eps} p_i * (1 - exp(eps - l_i)).
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np
from scipy import signal, stats

_TAIL_MASS = 1e-15


class PrivacyLossDistribution:
    """Discretized privacy loss distribution (pessimistic estimate)."""

    def __init__(self, probs: np.ndarray, offset: int, interval: float,
                 infinity_mass: float):
        # probs[i] is the mass at loss (offset + i) * interval.
        self._probs = np.asarray(probs, dtype=np.float64)
        self._offset = int(offset)
        self._interval = float(interval)
        self._infinity_mass = float(infinity_mass)

    @property
    def value_discretization_interval(self) -> float:
        return self._interval

    @property
    def infinity_mass(self) -> float:
        return self._infinity_mass

    def losses_and_probs(self):
        losses = (self._offset +
                  np.arange(len(self._probs))) * self._interval
        return losses, self._probs

    def compose(self,
                other: "PrivacyLossDistribution") -> "PrivacyLossDistribution":
        """Composition of two independent mechanisms: loss variables add."""
        if not math.isclose(self._interval, other._interval):
            raise ValueError(
                "Cannot compose PLDs with different discretization intervals: "
                f"{self._interval} vs {other._interval}")
        probs = signal.fftconvolve(self._probs, other._probs)
        probs = np.clip(probs, 0.0, None)
        inf_mass = 1.0 - (1.0 - self._infinity_mass) * (1.0 -
                                                        other._infinity_mass)
        return PrivacyLossDistribution(probs, self._offset + other._offset,
                                       self._interval, inf_mass)

    def self_compose(self, count: int) -> "PrivacyLossDistribution":
        """Composes the mechanism with itself ``count`` times (square & multiply)."""
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        result: Optional[PrivacyLossDistribution] = None
        base = self
        n = count
        while n:
            if n & 1:
                result = base if result is None else result.compose(base)
            n >>= 1
            if n:
                base = base.compose(base)
        return result

    def get_delta_for_epsilon(self, epsilon: float) -> float:
        """Hockey-stick divergence delta(eps)."""
        losses, probs = self.losses_and_probs()
        mask = losses > epsilon
        delta = self._infinity_mass
        if np.any(mask):
            tail_losses = losses[mask]
            tail_probs = probs[mask]
            delta += float(
                np.sum(tail_probs * -np.expm1(epsilon - tail_losses)))
        return min(max(delta, 0.0), 1.0)

    def get_epsilon_for_delta(self, delta: float) -> float:
        """Smallest eps with delta(eps) <= delta; inf if unreachable."""
        if delta < 0:
            raise ValueError(f"delta must be >= 0, got {delta}")
        if self._infinity_mass > delta:
            return math.inf
        losses, _ = self.losses_and_probs()
        hi = float(losses[-1]) if len(losses) else 0.0
        if self.get_delta_for_epsilon(hi) > delta:
            # Only possible via float round-off at the top of the grid.
            return hi + self._interval
        lo = float(losses[0]) - self._interval if len(losses) else -1.0
        if self.get_delta_for_epsilon(lo) <= delta:
            return max(lo, 0.0) if delta > 0 else lo
        for _ in range(100):
            mid = 0.5 * (lo + hi)
            if self.get_delta_for_epsilon(mid) <= delta:
                hi = mid
            else:
                lo = mid
            if hi - lo < 1e-9:
                break
        return hi


def _discretize_from_cdf(cdf, lo: float, hi: float, interval: float,
                         infinity_mass: float) -> PrivacyLossDistribution:
    """Builds a pessimistic PLD from the CDF of the loss variable.

    ``cdf(l)`` must be P(L <= l) for l in [lo, hi]; all mass in [lo, hi].
    Mass in the half-open bin ((i-1)*d, i*d] lands on grid point i*d, i.e.
    each loss is rounded up.
    """
    lo_idx = math.floor(lo / interval)
    hi_idx = math.ceil(hi / interval)
    grid = np.arange(lo_idx, hi_idx + 1) * interval
    cdf_vals = np.clip(np.array([cdf(g) for g in grid]), 0.0, 1.0)
    cdf_vals[-1] = 1.0 - infinity_mass if infinity_mass else cdf_vals[-1]
    probs = np.diff(cdf_vals, prepend=0.0)
    probs = np.clip(probs, 0.0, None)
    return PrivacyLossDistribution(probs, lo_idx, interval, infinity_mass)


def from_laplace_mechanism(
        parameter: float,
        sensitivity: float = 1.0,
        value_discretization_interval: float = 1e-4
) -> PrivacyLossDistribution:
    """PLD of the Laplace mechanism with noise scale ``parameter``.

    For x ~ Lap(0, b) vs Lap(s, b) the loss is L(x) = (|x - s| - |x|)/b:
    an atom of mass 1/2 at s/b (x <= 0), an atom of mass exp(-s/b)/2 at -s/b
    (x >= s), and continuously distributed in between with
    P(L <= l) = exp((l*b - s)/(2b))/2.
    """
    if parameter <= 0:
        raise ValueError(f"Laplace parameter must be positive: {parameter}")
    b = parameter / sensitivity  # scale in units of sensitivity
    max_loss = 1.0 / b

    def cdf(l: float) -> float:
        if l < -max_loss:
            return 0.0
        if l >= max_loss:
            return 1.0
        return 0.5 * math.exp((l - max_loss) / 2.0)

    return _discretize_from_cdf(cdf, -max_loss, max_loss,
                                value_discretization_interval, 0.0)


def from_gaussian_mechanism(
        standard_deviation: float,
        sensitivity: float = 1.0,
        value_discretization_interval: float = 1e-4
) -> PrivacyLossDistribution:
    """PLD of the Gaussian mechanism with std ``standard_deviation``.

    For x ~ N(0, sigma^2) vs N(s, sigma^2) the loss under P is
    L ~ N(s^2/(2 sigma^2), s^2/sigma^2) (mu = s/sigma in loss-std units).
    Tails beyond ``_TAIL_MASS`` quantiles are truncated; the upper tail is
    pessimistically folded into infinity_mass.
    """
    if standard_deviation <= 0:
        raise ValueError(f"std must be positive: {standard_deviation}")
    sigma = standard_deviation / sensitivity
    mu = 1.0 / (2.0 * sigma * sigma)
    loss_std = 1.0 / sigma
    lo = mu + loss_std * stats.norm.ppf(_TAIL_MASS)
    hi = mu + loss_std * stats.norm.isf(_TAIL_MASS)
    upper_tail = _TAIL_MASS

    def cdf(l: float) -> float:
        return float(stats.norm.cdf((l - mu) / loss_std))

    return _discretize_from_cdf(cdf, lo, hi, value_discretization_interval,
                                upper_tail)


def from_privacy_parameters(
        eps: float,
        delta: float,
        value_discretization_interval: float = 1e-4
) -> PrivacyLossDistribution:
    """Canonical PLD of an arbitrary (eps, delta)-DP mechanism.

    The dominating pair for a generic (eps, delta)-DP mechanism puts mass
    delta at +infinity and splits the remaining mass between losses +eps and
    -eps with odds e^eps : 1 (reference semantics:
    dp_accounting from_privacy_parameters, used at budget_accounting.py:612).
    """
    interval = value_discretization_interval
    idx_hi = math.ceil(eps / interval)
    idx_lo = math.ceil(-eps / interval)  # round up: pessimistic
    probs = np.zeros(idx_hi - idx_lo + 1)
    p_hi = (1.0 - delta) * math.exp(eps) / (1.0 + math.exp(eps))
    p_lo = (1.0 - delta) / (1.0 + math.exp(eps))
    probs[-1] = p_hi
    probs[0] += p_lo
    return PrivacyLossDistribution(probs, idx_lo, interval, delta)
