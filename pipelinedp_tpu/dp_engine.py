"""DPEngine: composes backend + combiners + bounders + selection into the
lazy DP aggregation graph.

Parity: pipeline_dp/dp_engine.py (DPEngine :31, aggregate :65, _aggregate
:109-187, select_partitions :212, _select_partitions :234, _drop_partitions
:290, _add_empty_public_partitions :298, _select_private_partitions_internal
:315-371, _create_contribution_bounder :380-400,
calculate_private_contribution_bounds :450, add_dp_noise :551, _annotate
:609).

Graph (aggregate): extract -> drop non-public -> bound contributions ->
reduce per key -> add empty publics -> select private partitions -> compute
DP metrics -> post-aggregation threshold. Everything is lazy; budgets
resolve via BudgetAccountant.compute_budgets() before execution.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple, Union

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import combiners
from pipelinedp_tpu import contribution_bounders
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import partition_selection
from pipelinedp_tpu import pipeline_functions
from pipelinedp_tpu import report_generator as report_generator_lib
from pipelinedp_tpu import sampling_utils
from pipelinedp_tpu.aggregate_params import (
    AddDPNoiseParams, AggregateParams,
    CalculatePrivateContributionBoundsParams, MechanismType, Metric, Metrics,
    PartitionSelectionStrategy, PrivateContributionBounds,
    SelectPartitionsParams)
from pipelinedp_tpu.backends import base
from pipelinedp_tpu.data_extractors import DataExtractors
from pipelinedp_tpu.report_generator import ExplainComputationReport


class DPEngine:
    """Performs DP aggregations on a pipeline backend."""

    def __init__(self, budget_accountant: budget_accounting.BudgetAccountant,
                 backend: base.PipelineBackend):
        self._budget_accountant = budget_accountant
        self._backend = backend
        self._report_generators = []

    # -- explain-computation plumbing ---------------------------------------

    @property
    def _current_report_generator(self):
        return self._report_generators[-1]

    def _add_report_generator(self,
                              params,
                              method_name: str,
                              is_public_partition: Optional[bool] = None):
        self._report_generators.append(
            report_generator_lib.ReportGenerator(params, method_name,
                                                 is_public_partition))

    def _add_report_stage(self, stage_description):
        self._current_report_generator.add_stage(stage_description)

    def _add_report_stages(self, stages_description):
        for stage in stages_description:
            self._add_report_stage(stage)

    def explain_computations_report(self):
        return [generator.report() for generator in self._report_generators]

    # -- aggregate ----------------------------------------------------------

    def aggregate(self,
                  col,
                  params: AggregateParams,
                  data_extractors: DataExtractors,
                  public_partitions=None,
                  out_explain_computation_report: Optional[
                      ExplainComputationReport] = None):
        """Computes DP metrics per partition key.

        Returns a collection of (partition_key, metrics namedtuple). With
        public_partitions=None partitions are selected privately.
        """
        self._check_aggregate_params(col, params, data_extractors)
        self._check_budget_accountant_compatibility(
            public_partitions is not None, params.metrics,
            params.custom_combiners is not None)
        with self._budget_accountant.scope(weight=params.budget_weight):
            self._add_report_generator(params, "aggregate",
                                       public_partitions is not None)
            if out_explain_computation_report is not None:
                out_explain_computation_report._set_report_generator(
                    self._current_report_generator)
            col = self._aggregate(col, params, data_extractors,
                                  public_partitions)
            budget = self._budget_accountant._compute_budget_for_aggregation(
                params.budget_weight)
            return self._annotate(col, params=params, budget=budget)

    def _aggregate(self, col, params: AggregateParams,
                   data_extractors: DataExtractors, public_partitions):
        if params.custom_combiners:
            combiner = combiners.create_compound_combiner_with_custom_combiners(
                params, self._budget_accountant, params.custom_combiners)
        else:
            combiner = self._create_compound_combiner(params)

        col = self._extract_columns(col, data_extractors)
        # col: (privacy_id, partition_key, value)

        if (public_partitions is not None and
                not params.public_partitions_already_filtered):
            col = self._drop_partitions(col,
                                        public_partitions,
                                        partition_extractor=lambda row: row[1])
            self._add_report_stage(
                "Public partition selection: dropped non public partitions")

        if not params.contribution_bounds_already_enforced:
            bounder = self._create_contribution_bounder(
                params, combiner.expects_per_partition_sampling())
            col = bounder.bound_contributions(col, params, self._backend,
                                              self._current_report_generator,
                                              combiner.create_accumulator)
            # col: ((privacy_id, partition_key), accumulator)
            col = self._backend.map_tuple(col, lambda pid_pk, acc:
                                          (pid_pk[1], acc), "Drop privacy id")
        else:
            col = self._backend.map(col, lambda row: row[1:],
                                    "Remove privacy_id")
            col = self._backend.map_values(
                col, lambda value: combiner.create_accumulator([value]),
                "Wrap values into accumulators")
        # col: (partition_key, accumulator)

        if public_partitions:
            col = self._add_empty_public_partitions(
                col, public_partitions, combiner.create_accumulator)

        col = self._backend.combine_accumulators_per_key(
            col, combiner, "Reduce accumulators per partition key")

        if (public_partitions is None and
                not params.post_aggregation_thresholding):
            max_rows_per_privacy_id = 1
            if params.contribution_bounds_already_enforced:
                # Without privacy ids in the input we can only lower-bound the
                # number of privacy units per partition from the row count.
                max_rows_per_privacy_id = (
                    params.max_contributions or
                    params.max_contributions_per_partition)
            col = self._select_private_partitions_internal(
                col, params.max_partitions_contributed,
                max_rows_per_privacy_id, params.partition_selection_strategy,
                params.pre_threshold)

        self._add_report_stages(combiner.explain_computation())
        col = self._backend.map_values(col, combiner.compute_metrics,
                                       "Compute DP metrics")

        if params.post_aggregation_thresholding:
            col = self._drop_partitions_under_threshold(col)
        return col

    # -- select_partitions --------------------------------------------------

    def select_partitions(self, col, params: SelectPartitionsParams,
                          data_extractors: DataExtractors):
        """Returns a DP-selected collection of partition keys."""
        self._check_select_private_partitions(col, params, data_extractors)
        self._check_budget_accountant_compatibility(False, [], False)
        with self._budget_accountant.scope(weight=params.budget_weight):
            self._add_report_generator(params, "select_partitions")
            col = self._select_partitions(col, params, data_extractors)
            budget = self._budget_accountant._compute_budget_for_aggregation(
                params.budget_weight)
            return self._annotate(col, params=params, budget=budget)

    def _select_partitions(self, col, params: SelectPartitionsParams,
                           data_extractors: DataExtractors):
        max_partitions = params.max_partitions_contributed
        col = self._backend.map(
            col, lambda row: (data_extractors.privacy_id_extractor(row),
                              data_extractors.partition_extractor(row)),
            "Extract (privacy_id, partition_key)")
        col = self._backend.group_by_key(col, "Group by privacy_id")

        # Dedupe each privacy id's partitions and L0-sample them. Note: not
        # scalable if one privacy id contributes to an extreme number of
        # partitions (same caveat as the reference, dp_engine.py:252-253).
        def sample_unique(pid_and_pks):
            pid, pks = pid_and_pks
            unique_pks = list(set(pks))
            sampled = sampling_utils.choose_from_list_without_replacement(
                unique_pks, max_partitions)
            return ((pid, pk) for pk in sampled)

        col = self._backend.flat_map(col, sample_unique,
                                     "Sample cross-partition contributions")
        compound = combiners.CompoundCombiner([], return_named_tuple=False)
        col = self._backend.map_tuple(
            col, lambda pid, pk: (pk, compound.create_accumulator([])),
            "Drop privacy id and add accumulator")
        col = self._backend.combine_accumulators_per_key(
            col, compound, "Combine accumulators per partition key")
        col = self._select_private_partitions_internal(
            col, max_partitions, 1, params.partition_selection_strategy,
            params.pre_threshold)
        return self._backend.keys(
            col, "Drop accumulators, keep only partition keys")

    # -- helpers ------------------------------------------------------------

    def _drop_partitions(self, col, partitions,
                         partition_extractor: Callable):
        col = pipeline_functions.key_by(self._backend, col,
                                        partition_extractor,
                                        "Key by partition")
        col = self._backend.filter_by_key(col, partitions,
                                          "Filtering out partitions")
        return self._backend.values(col, "Drop key")

    def _add_empty_public_partitions(self, col, public_partitions,
                                     aggregator_fn):
        self._add_report_stage(
            "Adding empty partitions for public partitions that are missing "
            "in data")
        public_partitions = self._backend.to_collection(
            public_partitions, col, "Public partitions to collection")
        empty = self._backend.map(
            public_partitions, lambda pk: (pk, aggregator_fn([])),
            "Build empty accumulators")
        return self._backend.flatten(
            (col, empty), "Join public partitions with partitions from data")

    def _select_private_partitions_internal(
            self, col, max_partitions_contributed: int,
            max_rows_per_privacy_id: int,
            strategy: PartitionSelectionStrategy,
            pre_threshold: Optional[int]):
        """Filters (pk, compound accumulator) by DP partition selection."""
        budget = self._budget_accountant.request_budget(
            mechanism_type=MechanismType.GENERIC)

        def filter_fn(budget, max_partitions, max_rows_per_privacy_id,
                      strategy, pre_threshold, row) -> bool:
            # Lazily creates the selection strategy (budget resolves after
            # graph construction, and strategy objects don't serialize).
            row_count, _ = row[1]
            privacy_id_count = (row_count + max_rows_per_privacy_id -
                                1) // max_rows_per_privacy_id
            selector = partition_selection.create_partition_selection_strategy(
                strategy, budget.eps, budget.delta, max_partitions,
                pre_threshold)
            return selector.should_keep(privacy_id_count)

        filter_fn = functools.partial(filter_fn, budget,
                                      max_partitions_contributed,
                                      max_rows_per_privacy_id, strategy,
                                      pre_threshold)
        pre_threshold_str = (f", pre_threshold={pre_threshold}"
                             if pre_threshold else "")
        self._add_report_stage(
            lambda: f"Private Partition selection: using {strategy.value} "
                    f"method with (eps={budget.eps}, delta={budget.delta}"
                    f"{pre_threshold_str})")
        return self._backend.filter(col, filter_fn,
                                    "Filter private partitions")

    def _create_compound_combiner(
            self, params: AggregateParams) -> combiners.CompoundCombiner:
        return combiners.create_compound_combiner(params,
                                                  self._budget_accountant)

    def _create_contribution_bounder(
            self, params: AggregateParams,
            expects_per_partition_sampling: bool
    ) -> contribution_bounders.ContributionBounder:
        if params.max_contributions:
            return (contribution_bounders.
                    SamplingPerPrivacyIdContributionBounder())
        if params.perform_cross_partition_contribution_bounding:
            if expects_per_partition_sampling:
                return (contribution_bounders.
                        SamplingCrossAndPerPartitionContributionBounder())
            return (contribution_bounders.
                    SamplingCrossPartitionContributionBounder())
        if expects_per_partition_sampling:
            return contribution_bounders.LinfSampler()
        return contribution_bounders.NoOpSampler()

    def _extract_columns(self, col, data_extractors: DataExtractors):
        pid_extractor = data_extractors.privacy_id_extractor
        if pid_extractor is None:
            pid_extractor = lambda row: None
        value_extractor = data_extractors.value_extractor
        if value_extractor is None:
            # COUNT-only pipelines don't need values.
            value_extractor = lambda row: None
        return self._backend.map(
            col, lambda row: (pid_extractor(row),
                              data_extractors.partition_extractor(row),
                              value_extractor(row)),
            "Extract (privacy_id, partition_key, value)")

    # -- validation ---------------------------------------------------------

    def _check_aggregate_params(self,
                                col,
                                params: AggregateParams,
                                data_extractors: DataExtractors,
                                check_data_extractors: bool = True):
        if params is not None and isinstance(params, AggregateParams) and \
                params.max_contributions is not None:
            supported = {
                Metrics.PRIVACY_ID_COUNT, Metrics.COUNT, Metrics.SUM,
                Metrics.MEAN
            }
            unsupported = set(params.metrics or []) - supported
            if unsupported:
                raise NotImplementedError(
                    f"max_contributions is not supported for {unsupported}")
        _check_col(col)
        if params is None:
            raise ValueError("params must be set to a valid AggregateParams")
        if not isinstance(params, AggregateParams):
            raise TypeError("params must be set to a valid AggregateParams")
        if check_data_extractors:
            _check_data_extractors(data_extractors)
        if params.contribution_bounds_already_enforced:
            if Metrics.PRIVACY_ID_COUNT in (params.metrics or []):
                raise ValueError(
                    "PRIVACY_ID_COUNT cannot be computed when "
                    "contribution_bounds_already_enforced is True.")
        if params.post_aggregation_thresholding:
            if Metrics.PRIVACY_ID_COUNT not in (params.metrics or []):
                raise ValueError("When post_aggregation_thresholding = True, "
                                 "PRIVACY_ID_COUNT must be in metrics")

    def _check_select_private_partitions(self, col,
                                         params: SelectPartitionsParams,
                                         data_extractors: DataExtractors):
        _check_col(col)
        if params is None:
            raise ValueError(
                "params must be set to a valid SelectPartitionsParams")
        if not isinstance(params, SelectPartitionsParams):
            raise TypeError(
                "params must be set to a valid SelectPartitionsParams")
        if (not isinstance(params.max_partitions_contributed, int) or
                params.max_partitions_contributed <= 0):
            raise ValueError("params.max_partitions_contributed must be set "
                             "(to a positive integer)")
        if data_extractors is None:
            raise ValueError("data_extractors must be set to a DataExtractors")
        if not isinstance(data_extractors, DataExtractors):
            raise TypeError("data_extractors must be set to a DataExtractors")

    def _check_budget_accountant_compatibility(
            self, is_public_partition: bool, metrics: Sequence[Metric],
            custom_combiner: bool) -> None:
        if isinstance(self._budget_accountant,
                      budget_accounting.NaiveBudgetAccountant):
            return
        if not is_public_partition:
            raise NotImplementedError("PLD budget accounting does not support "
                                      "private partition selection")
        supported = {
            Metrics.COUNT, Metrics.PRIVACY_ID_COUNT, Metrics.SUM, Metrics.MEAN
        }
        unsupported = set(metrics) - supported
        if unsupported:
            raise NotImplementedError(
                f"Metrics {unsupported} do not support PLD budget accounting")
        if custom_combiner:
            raise ValueError(
                "PLD budget accounting does not support custom combiners")

    # -- private contribution bounds ----------------------------------------

    def calculate_private_contribution_bounds(
            self,
            col,
            params: CalculatePrivateContributionBoundsParams,
            data_extractors: DataExtractors,
            partitions: Any,
            partitions_already_filtered: bool = False):
        """DP computation of max_partitions_contributed (L0 bound) via the
        exponential mechanism over dataset histograms.

        Supported for COUNT / PRIVACY_ID_COUNT aggregations. Returns a
        1-element collection with PrivateContributionBounds.
        """
        self._check_calculate_private_contribution_bounds_params(
            col, params, data_extractors)
        if not partitions_already_filtered:
            col = self._drop_partitions(col, partitions,
                                        data_extractors.partition_extractor)
        try:
            from pipelinedp_tpu.dataset_histograms import computing_histograms
            from pipelinedp_tpu.private_contribution_bounds import (
                PrivateL0Calculator)
        except ImportError as e:
            raise NotImplementedError(
                "calculate_private_contribution_bounds requires the dataset "
                "histograms subsystem, which is not available in this "
                "build.") from e
        histograms = computing_histograms.compute_dataset_histograms(
            col, data_extractors, self._backend)
        l0_calculator = PrivateL0Calculator(params, partitions, histograms,
                                            self._backend)
        return pipeline_functions.collect_to_container(
            self._backend,
            {"max_partitions_contributed": l0_calculator.calculate()},
            PrivateContributionBounds,
            "Collect calculated private contribution bounds into "
            "PrivateContributionBounds dataclass")

    def _check_calculate_private_contribution_bounds_params(
            self,
            col,
            params: CalculatePrivateContributionBoundsParams,
            data_extractors: DataExtractors,
            check_data_extractors: bool = True):
        _check_col(col)
        if params is None:
            raise ValueError(
                "params must be set to a valid "
                "CalculatePrivateContributionBoundsParams")
        if not isinstance(params, CalculatePrivateContributionBoundsParams):
            raise TypeError(
                "params must be set to a valid "
                "CalculatePrivateContributionBoundsParams")
        if check_data_extractors:
            _check_data_extractors(data_extractors)

    # -- post-aggregation thresholding / add_dp_noise -----------------------

    def _drop_partitions_under_threshold(self, col):
        self._add_report_stage("Drop partitions which have noised "
                               "privacy_id_count less than threshold.")
        return self._backend.filter(
            col, lambda row: row[1].privacy_id_count is not None,
            "Drop partitions under threshold")

    def add_dp_noise(self,
                     col,
                     params: AddDPNoiseParams,
                     out_explain_computation_report: Optional[
                         ExplainComputationReport] = None):
        """Adds calibrated DP noise to pre-aggregated (pk, value) pairs.

        Does NOT enforce sensitivity: the caller guarantees the provided
        l0/linf bounds hold and that partition keys are public/DP-selected.
        """
        mechanism_type = params.noise_kind.convert_to_mechanism_type()
        mechanism_spec = self._budget_accountant.request_budget(mechanism_type)
        sensitivities = dp_computations.Sensitivities(
            l0=params.l0_sensitivity, linf=params.linf_sensitivity)
        self._add_report_generator(params, "add_dp_noise",
                                   is_public_partition=True)
        if out_explain_computation_report is not None:
            out_explain_computation_report._set_report_generator(
                self._current_report_generator)

        def create_mechanism() -> dp_computations.AdditiveMechanism:
            return dp_computations.create_additive_mechanism(
                mechanism_spec, sensitivities)

        self._add_report_stage(
            lambda: f"Adding {create_mechanism().noise_kind} noise with "
                    f"parameter {create_mechanism().noise_parameter}")
        anonymized = self._backend.map_values(
            col, lambda value: create_mechanism().add_noise(float(value)),
            "Add noise")
        budget = self._budget_accountant._compute_budget_for_aggregation(
            params.budget_weight)
        return self._annotate(anonymized, params=params, budget=budget)

    def _annotate(self, col, params, budget):
        return self._backend.annotate(col,
                                      "annotation",
                                      params=params,
                                      budget=budget)


def _check_col(col):
    if col is None or _is_empty_local(col):
        raise ValueError("col must be non-empty")


def _is_empty_local(col) -> bool:
    try:
        return len(col) == 0
    except TypeError:
        return False


def _check_data_extractors(data_extractors: DataExtractors):
    if data_extractors is None:
        raise ValueError("data_extractors must be set to a DataExtractors")
    if not isinstance(data_extractors, DataExtractors):
        raise TypeError("data_extractors must be set to a DataExtractors")
